"""Rollout controller (horovod_tpu/serving/router/rollout.py).

Two layers of proof, mirroring tests/test_router.py:

* **Unit** (a fake supervisor + registry pair that completes drains
  and respawns synchronously, and canned ``/stats`` snapshots wired
  straight into the controller's fetch hook): the full state machine
  — happy-path promotion, candidate splitting (spec fields vs engine
  knobs), the refusal rules, a deterministic fault at every one of
  the four ``rollout_*`` sites, canary SLO/score/crash/abort trips,
  drain-overrun trips, the journal format, and the recovery decision
  rule (journaled ``rolling`` → resume forward, else roll back).
  Every trip must leave the fake fleet convergent: either every slot
  at the candidate config or every slot back at the incumbent, never
  mixed, with the override table empty.
* **Chaos** (real replica subprocesses behind a real supervisor +
  router): the acceptance invariant — a full rolling promotion under
  concurrent load drops zero requests and converges every replica's
  live ``/stats`` config generation; SIGKILLing the canary mid-score
  trips an automatic rollback that converges back to the incumbent
  with every request still resolving oracle-identical; and a
  supervisor that died mid-rollout (its journal ends without an
  ``end`` event) recovers deterministically from the journal alone.
"""

import json
import os
import signal
import tempfile
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving.router import (
    ReplicaEndpoint,
    ReplicaRegistry,
    ReplicaSpec,
    ReplicaSupervisor,
    RolloutController,
    RolloutError,
    RouterMetrics,
    RouterServer,
)
from horovod_tpu.serving.router import rollout as rollout_mod

pytestmark = pytest.mark.rollout


# ---------------------------------------------------------------------------
# fakes: a supervisor + registry pair with synchronous drains/respawns
# ---------------------------------------------------------------------------


class _FakeHandle:
    def __init__(self, slot, gen):
        self.slot = slot
        self.gen = gen

    @property
    def rid(self):
        return f"r{self.slot}g{self.gen}"


class _FakeStatus:
    """Just enough ReplicaStatus surface for the controller: an
    endpoint with a rid/base_url and the polled config generation."""

    def __init__(self, rid, config_gen=0):
        self.endpoint = ReplicaEndpoint(rid, "127.0.0.1", 1)
        self.config_gen = config_gen


class _FakeRegistry:
    """The registry surface the controller touches, no HTTP."""

    def __init__(self):
        self.metrics = RouterMetrics()
        self.poll_timeout = 1.0
        self._thread = object()   # "poll thread running"
        self.routable = {}        # rid -> config_gen
        self.canary_rid = None
        self.canary_weight = 0.0
        self.canary_history = []

    def is_routable(self, rid):
        return rid in self.routable

    def in_rotation(self):
        return [_FakeStatus(rid, g) for rid, g in self.routable.items()]

    statuses = in_rotation

    def poll_now(self):
        pass

    def set_canary(self, rid, weight):
        self.canary_rid = rid
        self.canary_weight = weight
        self.canary_history.append(rid)

    def clear_canary(self):
        self.canary_rid = None
        self.canary_weight = 0.0


class _FakeSupervisor:
    """Drain-and-respawn completes synchronously: ``drain_slot`` swaps
    in a NEW handle one generation up (the real supervisor's exit
    watcher does this asynchronously) and marks the new rid routable
    at its slot spec's config generation.  ``drain_mode`` scripts the
    failure shapes the trips need."""

    def __init__(self, spec, n_replicas, registry, *,
                 drain_mode="ok", shutdown_grace=0.05):
        self._spec = spec
        self.n_replicas = n_replicas
        self.registry = registry
        self._shutdown_grace = shutdown_grace
        self._slot_specs = {}
        self._journal_dir = None
        self.handles = {}
        self.drained = []          # (slot, reason) in drain order
        self.drain_mode = drain_mode
        for slot in range(n_replicas):
            h = _FakeHandle(slot, 0)
            self.handles[slot] = h
            registry.routable[h.rid] = spec.config_gen

    @property
    def spec(self):
        return self._spec

    def set_base_spec(self, spec):
        self._spec = spec
        self._slot_specs.clear()

    def slot_spec(self, slot):
        return self._slot_specs.get(slot, self._spec)

    def set_slot_spec(self, slot, spec):
        self._slot_specs[slot] = spec

    def clear_slot_spec(self, slot):
        self._slot_specs.pop(slot, None)

    def handle(self, slot):
        return self.handles.get(slot)

    def respawn(self, slot, routable=True):
        old = self.handles[slot]
        self.registry.routable.pop(old.rid, None)
        h = _FakeHandle(slot, old.gen + 1)
        self.handles[slot] = h
        if routable:
            self.registry.routable[h.rid] = \
                self.slot_spec(slot).config_gen
        return h

    def drain_slot(self, slot, reason="rollout"):
        self.drained.append((slot, reason))
        if self.drain_mode == "stuck":
            return self.handles[slot]     # never exits, never respawns
        if self.drain_mode == "unroutable":
            return self.respawn(slot, routable=False)
        return self.respawn(slot)


def _snap(tokens=0, ticks=0, preempt=0, ttft=None):
    """One cumulative /stats payload in the replica contract shape."""
    hists = {}
    for cls, buckets in (ttft or {}).items():
        total = sum(buckets.values())
        hists[cls] = {"count": total, "sum": 0.0, "buckets": buckets}
    return {"tokens_generated": tokens, "decode_ticks": ticks,
            "preemptions": preempt, "ttft_seconds_by_class": hists}


def _wire_stats(ctl, feeds):
    """Replace the controller's HTTP fetch with canned snapshot
    sequences: ``feeds[rid]`` is a list consumed one per fetch (the
    last entry repeats, so counters keep their final plateau)."""
    cursors = {}

    def fetch(st):
        rid = st.endpoint.rid
        seq = feeds.get(rid)
        if not seq:
            return None
        i = cursors.get(rid, 0)
        cursors[rid] = i + 1
        return seq[min(i, len(seq) - 1)]

    ctl._fetch_stats = fetch


def _controller(sup, **kw):
    kw.setdefault("window_s", 0.01)
    kw.setdefault("canary_windows", 1)
    kw.setdefault("drain_margin", 0.05)
    kw.setdefault("ready_timeout", 2.0)
    ctl = RolloutController(sup, **kw)
    # Default canned stats: a healthy, in-SLO window for everyone.
    good = [_snap(tokens=0, ticks=0),
            _snap(tokens=50, ticks=10,
                  ttft={"interactive": {"0.25": 5, "+Inf": 0}})]
    feeds = {}
    for slot in range(sup.n_replicas):
        for gen in range(6):
            feeds[f"r{slot}g{gen}"] = good
    _wire_stats(ctl, feeds)
    return ctl


def _assert_converged(sup, reg, spec):
    """The terminal invariant: every slot routable at ``spec``'s
    config generation, no slot overrides left behind."""
    assert sup._slot_specs == {}
    gens = {slot: reg.routable.get(sup.handles[slot].rid)
            for slot in range(sup.n_replicas)}
    assert gens == {s: spec.config_gen for s in range(sup.n_replicas)}, \
        f"fleet not converged: {gens}"


# ---------------------------------------------------------------------------
# unit: state machine
# ---------------------------------------------------------------------------


class TestRolloutMachine:
    def test_happy_path_promotes_fleet(self, tmp_path):
        reg = _FakeRegistry()
        sup = _FakeSupervisor(ReplicaSpec(seed=0), 3, reg)
        ctl = _controller(
            sup, journal_path=str(tmp_path / "rollout.jsonl"))
        st = ctl.start({"max_prefills_per_tick": 4, "page_size": 16})
        assert st["state"] in ("draining", "rebuilding", "canary",
                               "rolling", "done")
        assert ctl.wait(10.0)
        assert ctl.state == "done"
        assert ctl.trip_reason is None
        # promotion: candidate became the fleet-wide base spec
        assert sup.spec.config_gen == 1
        assert sup.spec.max_prefills_per_tick == 4       # spec field
        assert sup.spec.engine_knobs == {"page_size": 16}  # engine knob
        _assert_converged(sup, reg, sup.spec)
        # one drain per slot, in slot order, tagged with the target gen
        assert [s for s, _ in sup.drained] == [0, 1, 2]
        assert all("gen 1" in r for _, r in sup.drained)
        # the first rebuilt replica was the canary, then cleared
        assert reg.canary_history == ["r0g1"]
        assert reg.canary_rid is None
        snap = reg.metrics.snapshot()
        assert snap["rollouts_started"] == 1
        assert snap["rollout_promotions"] == 1
        assert snap["rollout_rollbacks"] == 0
        assert snap["rollout_steps"] == 3
        assert snap["rollout_active"] == 0
        status = ctl.status()
        assert status["config_generation"] == 1
        assert status["canary_score"] is not None
        for key in ("drain_slot0", "rebuild_slot0", "canary", "total"):
            assert key in status["step_durations_s"]
        # journal: start .. states .. end, with a score event
        events = [json.loads(l) for l in
                  (tmp_path / "rollout.jsonl").read_text().splitlines()]
        assert events[0]["e"] == "start"
        assert events[0]["config_gen"] == 1
        assert events[-1]["e"] == "end"
        assert events[-1]["state"] == "done"
        assert any(e["e"] == "score" for e in events)
        states = [e["s"] for e in events if e["e"] == "state"]
        assert states[0] == "draining" and states[-1] == "done"
        assert "rolling" in states

    def test_candidate_split_and_generation_bump(self):
        reg = _FakeRegistry()
        base = ReplicaSpec(seed=0, config_gen=3,
                           engine_knobs={"overlap": True})
        sup = _FakeSupervisor(base, 2, reg)
        ctl = _controller(sup)
        ctl.start({"slots": 8, "speculation_k": 2})
        assert ctl.wait(10.0)
        cand = ctl._candidate_spec
        assert cand.slots == 8                     # ReplicaSpec field
        assert cand.config_gen == 4                # bumped from base
        # new knob merged over the incumbent's existing knobs
        assert cand.engine_knobs == {"overlap": True, "speculation_k": 2}

    def test_refusals(self):
        reg = _FakeRegistry()
        sup = _FakeSupervisor(ReplicaSpec(seed=0), 2, reg)
        ctl = _controller(sup)
        with pytest.raises(RolloutError, match="non-empty"):
            ctl.start({})
        with pytest.raises(RolloutError, match="non-empty"):
            ctl.start("slots=8")
        # 1-replica fleet: the drain step would take 100% of capacity
        sup1 = _FakeSupervisor(ReplicaSpec(seed=0), 1, _FakeRegistry())
        with pytest.raises(RolloutError, match="allow_capacity_dip"):
            _controller(sup1).start({"slots": 8})
        # callable command factories carry no config to re-render
        supc = _FakeSupervisor(ReplicaSpec(seed=0), 2, _FakeRegistry())
        supc._spec = lambda slot, port: ["true"]
        with pytest.raises(RolloutError, match="callable"):
            _controller(supc).start({"slots": 8})

    def test_one_replica_with_capacity_dip_promotes(self):
        reg = _FakeRegistry()
        sup = _FakeSupervisor(ReplicaSpec(seed=0), 1, reg)
        ctl = _controller(sup, allow_capacity_dip=True)
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert ctl.state == "done"
        assert sup.spec.config_gen == 1
        _assert_converged(sup, reg, sup.spec)

    def test_double_start_refused_while_active(self):
        reg = _FakeRegistry()
        sup = _FakeSupervisor(ReplicaSpec(seed=0), 2, reg)
        ctl = _controller(sup, canary_windows=50, window_s=0.1)
        ctl.start({"slots": 8})
        try:
            with pytest.raises(RolloutError, match="already"):
                ctl.start({"slots": 16})
        finally:
            ctl.abort()
            assert ctl.wait(10.0)

    @pytest.mark.parametrize("site", ["rollout_drain", "rollout_rebuild",
                                      "rollout_canary", "rollout_promote"])
    def test_fault_at_every_site_converges_to_incumbent(self, site):
        """THE chaos invariant at unit scale: a deterministic injected
        fault at each of the four controller sites ends in a terminal
        rollback state with the whole fleet back at the incumbent
        config generation — never mixed, no overrides left."""
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 3, reg)
        inj = serving.FaultInjector([
            serving.FaultSpec(site=site, kind="raise")])
        ctl = _controller(sup, faults=inj)
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert ctl.state in ("rolled_back", "aborted")
        assert "InjectedFaultError" in ctl.trip_reason
        assert sup.spec is incumbent            # never promoted
        _assert_converged(sup, reg, incumbent)
        snap = reg.metrics.snapshot()
        assert snap["rollout_rollbacks"] == 1
        assert snap["rollout_promotions"] == 0
        assert snap["rollout_active"] == 0
        assert reg.canary_rid is None
        if site == "rollout_drain":
            # tripped before ANY slot was touched: nothing to recycle
            assert ctl.state == "aborted"
            assert sup.drained == []
        else:
            # slot 0 ran the candidate config and had to be recycled
            assert ctl.state == "rolled_back"
            assert sup.handles[0].gen == 2      # out and back

    def test_canary_slo_breach_rolls_back(self):
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 3, reg)
        ctl = _controller(sup)
        # Canary p99 lands in the 1.0s bucket: 2x the 0.5s interactive
        # SLO = 100% excess, over the 50% guard band.
        bad = [_snap(),
               _snap(tokens=50, ticks=10,
                     ttft={"interactive": {"0.25": 0, "1": 10,
                                           "+Inf": 0}})]
        good = [_snap(),
                _snap(tokens=50, ticks=10,
                      ttft={"interactive": {"0.25": 10, "+Inf": 0}})]
        _wire_stats(ctl, {"r0g1": bad, "r1g0": good, "r2g0": good})
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert "canary_slo_breach" in ctl.trip_reason
        assert "interactive" in ctl.trip_reason
        assert sup.spec is incumbent
        _assert_converged(sup, reg, incumbent)
        assert reg.metrics.snapshot()["rollout_rollbacks"] == 1

    def test_canary_score_below_incumbent_rolls_back(self):
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 3, reg)
        ctl = _controller(sup, min_score_delta=1.0)
        # In-SLO but much slower than the incumbents: 1 token/tick vs 8.
        slow = [_snap(), _snap(tokens=10, ticks=10)]
        fast = [_snap(), _snap(tokens=80, ticks=10)]
        _wire_stats(ctl, {"r0g1": slow, "r1g0": fast, "r2g0": fast})
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert "below incumbent" in ctl.trip_reason
        _assert_converged(sup, reg, incumbent)
        st = ctl.status()
        assert st["canary_score"] < st["incumbent_score"]

    def test_canary_crash_rolls_back(self):
        """The canary's handle generation moving during a scoring
        window (the exit watcher respawned it = it crashed) trips."""
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 3, reg)
        ctl = _controller(sup, window_s=0.2, canary_windows=5)
        ctl.start({"slots": 8})
        deadline = time.monotonic() + 5.0
        while reg.canary_rid is None and time.monotonic() < deadline:
            time.sleep(0.005)
        assert reg.canary_rid == "r0g1"
        sup.respawn(0)                          # crash + respawn
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert "canary" in ctl.trip_reason
        assert sup.spec is incumbent
        _assert_converged(sup, reg, incumbent)

    def test_operator_abort_rolls_back(self):
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 3, reg)
        ctl = _controller(sup, window_s=0.2, canary_windows=50)
        ctl.start({"slots": 8})
        deadline = time.monotonic() + 5.0
        while reg.canary_rid is None and time.monotonic() < deadline:
            time.sleep(0.005)
        ctl.abort()
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert ctl.trip_reason == "operator_abort"
        _assert_converged(sup, reg, incumbent)

    def test_drain_overrun_trips_bounded(self):
        """A slot that never exits its drain must not wedge the
        rollout: the budget (drain_timeout + shutdown_grace + margin)
        trips it into rollback."""
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0, drain_timeout=0.05)
        sup = _FakeSupervisor(incumbent, 2, reg, drain_mode="stuck",
                              shutdown_grace=0.05)
        ctl = _controller(sup, drain_margin=0.05)
        t0 = time.monotonic()
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert time.monotonic() - t0 < 5.0
        assert ctl.state == "rolled_back"
        assert "drain_timeout" in ctl.trip_reason
        assert sup.spec is incumbent
        # the recycle overran too (drains stay stuck) — overrides are
        # still cleared so the supervisor converges any future respawn
        assert sup._slot_specs == {}

    def test_rebuild_timeout_trips(self):
        """A respawn that never becomes routable trips within
        ready_timeout instead of waiting forever."""
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, 2, reg,
                              drain_mode="unroutable")
        ctl = _controller(sup, ready_timeout=0.2)
        ctl.start({"slots": 8})
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert "rebuild_timeout" in ctl.trip_reason
        assert sup.spec is incumbent
        assert sup._slot_specs == {}


# ---------------------------------------------------------------------------
# unit: journal + recovery decision rule
# ---------------------------------------------------------------------------


class TestRolloutRecovery:
    def _journal(self, path, events):
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps({"t": 0.0, **e}) + "\n")

    def _fleet(self, slot_gens):
        """A fake fleet whose live config generations are scripted —
        the state a restarted supervisor would observe by polling."""
        reg = _FakeRegistry()
        incumbent = ReplicaSpec(seed=0)
        sup = _FakeSupervisor(incumbent, len(slot_gens), reg)
        for slot, gen in enumerate(slot_gens):
            if gen:
                h = sup.handles[slot]
                reg.routable.pop(h.rid, None)
                h2 = _FakeHandle(slot, 1)
                sup.handles[slot] = h2
                reg.routable[h2.rid] = gen
        return reg, incumbent, sup

    def test_no_pending_rollout_returns_none(self, tmp_path):
        path = str(tmp_path / "rollout.jsonl")
        reg, _, sup = self._fleet([0, 0])
        ctl = _controller(sup, journal_path=path)
        assert ctl.recover() is None            # no journal at all
        self._journal(path, [
            {"e": "start", "candidate": {"slots": 8}, "config_gen": 1,
             "n_replicas": 2},
            {"e": "state", "s": "draining"},
            {"e": "end", "state": "rolled_back", "trip": "x"},
        ])
        assert ctl.recover() is None            # finished cleanly

    def test_recover_rolls_back_before_promotion_point(self, tmp_path):
        """SIGKILLed mid-canary (no ``rolling`` state journaled): the
        candidate was never deemed good — recovery recycles the one
        candidate-config slot back to the incumbent."""
        path = str(tmp_path / "rollout.jsonl")
        reg, incumbent, sup = self._fleet([1, 0, 0])
        self._journal(path, [
            {"e": "start", "candidate": {"slots": 8}, "config_gen": 1,
             "n_replicas": 3},
            {"e": "state", "s": "draining"},
            {"e": "state", "s": "rebuilding"},
            {"e": "state", "s": "canary"},
        ])
        ctl = _controller(sup, journal_path=path)
        st = ctl.recover()
        assert st is not None and st["state"] == "rolling_back"
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        assert sup.spec is incumbent
        _assert_converged(sup, reg, incumbent)
        # only the mismatched slot was recycled
        assert [s for s, _ in sup.drained] == [0]
        ev = [json.loads(l) for l in open(path)]
        assert any(e.get("e") == "recover" and e["forward"] is False
                   for e in ev)
        assert ev[-1]["e"] == "end"

    def test_recover_resumes_forward_past_promotion_point(
            self, tmp_path):
        """SIGKILLed while ``rolling`` (canary already scored good):
        recovery finishes the promotion — only the slots still at the
        incumbent generation are recycled, and the candidate becomes
        the base spec."""
        path = str(tmp_path / "rollout.jsonl")
        reg, incumbent, sup = self._fleet([1, 1, 0])
        self._journal(path, [
            {"e": "start", "candidate": {"slots": 8}, "config_gen": 1,
             "n_replicas": 3},
            {"e": "state", "s": "draining"},
            {"e": "state", "s": "rebuilding"},
            {"e": "state", "s": "canary"},
            {"e": "state", "s": "rolling"},
        ])
        ctl = _controller(sup, journal_path=path)
        st = ctl.recover()
        assert st is not None and st["state"] == "rolling"
        assert ctl.wait(10.0)
        assert ctl.state == "done"
        assert sup.spec.config_gen == 1
        assert sup.spec.slots == 8
        _assert_converged(sup, reg, sup.spec)
        assert [s for s, _ in sup.drained] == [2]

    def test_recover_tolerates_torn_tail(self, tmp_path):
        """A SIGKILL can tear the journal's final line mid-write; the
        reader must skip it, not crash or mis-decide."""
        path = str(tmp_path / "rollout.jsonl")
        reg, incumbent, sup = self._fleet([1, 0])
        self._journal(path, [
            {"e": "start", "candidate": {"slots": 8}, "config_gen": 1,
             "n_replicas": 2},
            {"e": "state", "s": "draining"},
        ])
        with open(path, "a") as f:
            f.write('{"t": 0.0, "e": "sta')   # torn write
        ctl = _controller(sup, journal_path=path)
        assert ctl.recover() is not None
        assert ctl.wait(10.0)
        assert ctl.state == "rolled_back"
        _assert_converged(sup, reg, incumbent)


# ---------------------------------------------------------------------------
# unit: scoring plumbing
# ---------------------------------------------------------------------------


class TestScoringWindows:
    def test_hist_delta_p99_diffs_cumulative_buckets(self):
        base = {"buckets": {"0.1": 100, "0.5": 0, "+Inf": 0}}
        now = {"buckets": {"0.1": 100, "0.5": 10, "+Inf": 0}}
        # all 10 WINDOWED observations sit in the 0.5 bucket — the 100
        # older ones in 0.1 must not drag the p99 down
        assert rollout_mod._hist_delta_p99(now, base) == 0.5
        # un-windowed, the 109th of 110 observations is still in the
        # 0.5 bucket (upper-edge convention, same as _Window._p99)
        assert rollout_mod._hist_delta_p99(now, None) == 0.5
        only_low = {"buckets": {"0.1": 100, "0.5": 1, "+Inf": 0}}
        assert rollout_mod._hist_delta_p99(only_low, None) == 0.1
        assert rollout_mod._hist_delta_p99(base, base) is None  # empty
        assert rollout_mod._hist_delta_p99({}, None) is None

    def test_stats_window_diffs_counters(self):
        w = rollout_mod._StatsWindow(_snap(tokens=100, ticks=20,
                                           preempt=3))
        out = w.close(_snap(tokens=160, ticks=30, preempt=4,
                            ttft={"interactive": {"0.25": 5,
                                                  "+Inf": 0}}))
        assert (out.tokens, out.ticks, out.preemptions) == (60, 10, 1)
        assert out.ttft_p99 == {"interactive": 0.25}

    def test_merge_windows_sums_counters_takes_worst_p99(self):
        from horovod_tpu.tuning import WindowStats
        merged = rollout_mod._merge_windows([
            WindowStats(ticks=10, tokens=50, preemptions=1,
                        ttft_p99={"interactive": 0.1}),
            WindowStats(ticks=20, tokens=80, preemptions=0,
                        ttft_p99={"interactive": 0.4, "batch": 1.0}),
        ])
        assert (merged.ticks, merged.tokens, merged.preemptions) \
            == (30, 130, 1)
        assert merged.ttft_p99 == {"interactive": 0.4, "batch": 1.0}


# ---------------------------------------------------------------------------
# unit: the POST/GET /rollout admin surface
# ---------------------------------------------------------------------------


def _http(base, path, payload=None, timeout=10):
    if payload is None:
        req = urllib.request.Request(base + path)
    else:
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode()
            if not isinstance(payload, bytes) else payload,
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestRolloutAdminEndpoint:
    def test_no_controller_is_typed_503(self):
        rt = RouterServer(ReplicaRegistry(), port=0,
                          own_registry_thread=False).start()
        try:
            base = "http://%s:%d" % rt.address
            code, body = _http(base, "/rollout")
            assert code == 503
            assert body["type"] == "no_rollout_controller"
            code, body = _http(base, "/rollout", {"candidate": {"x": 1}})
            assert code == 503
            assert body["type"] == "no_rollout_controller"
        finally:
            rt.stop()

    def test_admin_lifecycle_and_validation(self):
        reg = _FakeRegistry()
        sup = _FakeSupervisor(ReplicaSpec(seed=0), 2, reg)
        ctl = _controller(sup, window_s=0.2, canary_windows=100)
        rt = RouterServer(ReplicaRegistry(), port=0, rollout=ctl,
                          own_registry_thread=False).start()
        try:
            base = "http://%s:%d" % rt.address
            code, body = _http(base, "/rollout", b"not json")
            assert (code, body["type"]) == (400, "bad_request")
            code, body = _http(base, "/rollout", {"nope": 1})
            assert (code, body["type"]) == (400, "bad_request")
            code, body = _http(base, "/rollout", {"candidate": {}})
            assert (code, body["type"]) == (400, "bad_request")
            # a shape the CONTROLLER refuses (1-replica fleet) is a
            # typed bad_candidate, distinct from a malformed body
            sup1 = _FakeSupervisor(ReplicaSpec(seed=0), 1,
                                   _FakeRegistry())
            rt.rollout = _controller(sup1)
            code, body = _http(base, "/rollout",
                               {"candidate": {"slots": 8}})
            assert (code, body["type"]) == (400, "bad_candidate")
            rt.rollout = ctl
            # accepted: 202 + live status; visible through GET and the
            # router's own /stats
            code, body = _http(base, "/rollout",
                               {"candidate": {"slots": 8}})
            assert code == 202
            assert body["active"] is True
            assert body["config_generation"] == 1
            code, body = _http(base, "/rollout")
            assert code == 200 and body["active"] is True
            code, body = _http(base, "/stats")
            assert body["rollout"]["active"] is True
            # a second start while active is a 409, not a new rollout
            code, body = _http(base, "/rollout",
                               {"candidate": {"slots": 16}})
            assert (code, body["type"]) == (409, "rollout_active")
            # operator abort over HTTP unwinds it
            code, body = _http(base, "/rollout", {"abort": True})
            assert code == 200
            assert ctl.wait(10.0)
            assert ctl.state == "rolled_back"
            assert ctl.trip_reason == "operator_abort"
        finally:
            ctl.abort()
            ctl.wait(10.0)
            rt.stop()


# ---------------------------------------------------------------------------
# chaos: real replica processes, real kills
# ---------------------------------------------------------------------------


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _post(base, payload, timeout=60):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _replica_stats(reg):
    """rid -> parsed /stats for every replica currently registered."""
    out = {}
    for st in reg.statuses():
        try:
            with urllib.request.urlopen(
                    st.endpoint.base_url + "/stats", timeout=2.0) as r:
                out[st.endpoint.rid] = json.loads(r.read())
        except Exception:
            pass
    return out


def _load_loop(base, prompts, steps, stop, results, timeout=90):
    """Open-loop trickle: keep POSTing until told to stop, recording
    every (code, tokens) — the zero-drops ledger."""
    i = 0
    while not stop.is_set():
        p = prompts[i % len(prompts)]
        try:
            code, resp = _post(base, {"tokens": p,
                                      "max_new_tokens": steps},
                               timeout=timeout)
            results.append((p, code, resp))
        except Exception as e:
            results.append((p, None, repr(e)))
        i += 1
        time.sleep(0.05)


@pytest.mark.chaos
@pytest.mark.slow
class TestRolloutChaos:
    """Real subprocess fleets.  Slow (multi-replica spawns + XLA
    compiles per generation); tier-1 siblings: the TestRolloutMachine
    fault matrix and TestRolloutRecovery prove the same decision logic
    at unit scale every run."""

    N = 3

    def _fleet(self, n=None, spec=None, **sup_kw):
        spec = spec or ReplicaSpec(seed=0, slots=4, warm=(8,),
                                   tick_timeout=30.0, drain_timeout=3.0,
                                   request_timeout=90.0)
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        sup_kw.setdefault("unhealthy_grace", 1.5)
        sup_kw.setdefault("shutdown_grace", 2.0)
        sup_kw.setdefault("backoff_initial", 0.1)
        sup_kw.setdefault("journal_dir",
                          tempfile.mkdtemp(prefix="rollout_journal_"))
        sup = ReplicaSupervisor(spec, n or self.N, registry=reg,
                                **sup_kw)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup)
        return reg, sup, rt

    def test_full_promotion_under_load_converges_and_drops_nothing(
            self, model):
        """ACCEPTANCE: a replay-tunable candidate rolls through a
        3-replica fleet under open-loop load — every request resolves
        200 with oracle-identical greedy output (including the ones
        that failed over off draining replicas), zero 5xx, and every
        live replica's /stats reports the candidate generation."""
        params, cfg = model
        steps = 12
        rng = np.random.default_rng(7)
        prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                   for i in range(4)]
        # Oracle BEFORE the fleet exists: the XLA compile runs in a
        # pristine process (and off the single CPU the replicas are
        # about to saturate).
        oracle = {tuple(p): _ref_greedy(params, cfg, p, steps)
                  for p in prompts}
        reg, sup, rt = self._fleet()
        ctl = RolloutController(sup, canary_weight=0.3,
                                canary_windows=1, window_s=1.0,
                                ready_timeout=240.0)
        rt.rollout = ctl
        sup.start()
        rt.start()
        stop, results = threading.Event(), []
        loader = None
        try:
            assert sup.wait_ready(timeout=240), "fleet never ready"
            base = "http://%s:%d" % rt.address
            loader = threading.Thread(
                target=_load_loop,
                args=(base, prompts, steps, stop, results))
            loader.start()
            time.sleep(0.5)
            code, body = _http(base, "/rollout", {
                "candidate": {"max_prefills_per_tick": 4}})
            assert code == 202, body
            assert ctl.wait(480.0), f"rollout wedged in {ctl.state}"
            assert ctl.state == "done", ctl.trip_reason
            time.sleep(1.0)
        finally:
            stop.set()
            if loader is not None:
                loader.join(120.0)
            try:
                # convergence: every live replica at generation 1
                gens = {rid: s.get("config_generation")
                        for rid, s in _replica_stats(reg).items()}
                assert gens and set(gens.values()) == {1}, gens
                # the promoted spec is the base for future respawns
                assert sup.spec.config_gen == 1
                assert sup.spec.max_prefills_per_tick == 4
                snap = reg.metrics.snapshot()
                assert snap["rollout_promotions"] == 1
                assert snap["rollout_rollbacks"] == 0
            finally:
                rt.stop()
                sup.stop()
            # zero drops, zero rollout-attributable 5xx, every output
            # oracle-identical through drains and failovers
            assert results, "load loop recorded nothing"
            for p, code, resp in results:
                assert code == 200, (p, code, resp)
                assert resp["tokens"] == oracle[tuple(p)], p

    def test_sigkill_canary_rolls_back_and_converges(self, model):
        """SIGKILL the canary replica during its scoring window: the
        controller trips (crash/eviction), rolls the rebuilt slot back
        to the incumbent, and the fleet converges to generation 0 with
        every request still resolving oracle-identically."""
        params, cfg = model
        rng = np.random.default_rng(11)
        prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                   for i in range(4)]
        # Oracle precomputed for the same reason as the promotion test.
        oracle = {tuple(p): _ref_greedy(params, cfg, p, 12)
                  for p in prompts}
        reg, sup, rt = self._fleet()
        ctl = RolloutController(sup, canary_weight=0.3,
                                canary_windows=20, window_s=1.0,
                                ready_timeout=240.0)
        rt.rollout = ctl
        sup.start()
        rt.start()
        stop, results = threading.Event(), []
        loader = None
        try:
            assert sup.wait_ready(timeout=240), "fleet never ready"
            base = "http://%s:%d" % rt.address
            loader = threading.Thread(
                target=_load_loop, args=(base, prompts, 12, stop,
                                         results))
            loader.start()
            assert ctl.start({"max_prefills_per_tick": 4})["active"]
            deadline = time.monotonic() + 300.0
            while (ctl.state != "canary"
                   and time.monotonic() < deadline):
                assert ctl.active, \
                    f"tripped early: {ctl.state} {ctl.trip_reason}"
                time.sleep(0.05)
            assert ctl.state == "canary", "canary phase never reached"
            h = sup.handle(0)
            assert h is not None and h.gen == 1
            os.kill(h.pid, signal.SIGKILL)
            assert ctl.wait(480.0), f"rollout wedged in {ctl.state}"
            assert ctl.state == "rolled_back", ctl.state
            assert "canary" in ctl.trip_reason
            time.sleep(1.0)
        finally:
            stop.set()
            if loader is not None:
                loader.join(120.0)
            try:
                gens = {rid: s.get("config_generation")
                        for rid, s in _replica_stats(reg).items()}
                assert gens and set(gens.values()) == {0}, gens
                assert sup.spec.config_gen == 0
                snap = reg.metrics.snapshot()
                assert snap["rollout_rollbacks"] == 1
                assert snap["rollout_promotions"] == 0
            finally:
                rt.stop()
                sup.stop()
            assert results, "load loop recorded nothing"
            for p, code, resp in results:
                assert code == 200, (p, code, resp)
                assert resp["tokens"] == oracle[tuple(p)], p

    def test_supervisor_killed_mid_rollout_recovers_from_journal(
            self, model):
        """A supervisor SIGKILLed mid-rollout leaves (a) a journal
        with no ``end`` event and (b) one replica live at the
        candidate config.  A fresh controller's :meth:`recover` must
        converge the real fleet from the journal alone — here the
        kill landed before the promotion point, so it rolls back."""
        reg, sup, rt = self._fleet(n=2)
        jdir = sup._journal_dir
        path = os.path.join(jdir, "rollout.journal.jsonl")
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=240), "fleet never ready"
            # Reproduce the dead supervisor's on-disk + fleet state by
            # hand: slot 0 rebuilt at gen 1, journal cut off mid-canary
            # (exactly what its last fsync'd lines would be).
            candidate_spec = __import__("dataclasses").replace(
                sup.spec, max_prefills_per_tick=4, config_gen=1)
            sup.set_slot_spec(0, candidate_spec)
            old = sup.handle(0)
            sup.drain_slot(0, reason="rollout gen 1")
            deadline = time.monotonic() + 240.0
            while time.monotonic() < deadline:
                h = sup.handle(0)
                if (h is not None and h.gen > old.gen
                        and reg.is_routable(h.rid)):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("slot 0 never rebuilt")
            with open(path, "w") as f:
                for e in [
                    {"e": "start",
                     "candidate": {"max_prefills_per_tick": 4},
                     "config_gen": 1, "n_replicas": 2},
                    {"e": "state", "s": "draining"},
                    {"e": "state", "s": "rebuilding"},
                    {"e": "state", "s": "canary"},
                ]:
                    f.write(json.dumps({"t": 0.0, **e}) + "\n")
            gens = {rid: s.get("config_generation")
                    for rid, s in _replica_stats(reg).items()}
            assert sorted(gens.values()) == [0, 1], gens  # mixed!
            # ... supervisor process "restarts": a fresh controller
            ctl = RolloutController(sup, ready_timeout=240.0,
                                    journal_path=path)
            st = ctl.recover()
            assert st is not None and st["state"] == "rolling_back"
            assert ctl.wait(480.0), f"recovery wedged in {ctl.state}"
            assert ctl.state == "rolled_back"
            time.sleep(0.5)
            gens = {rid: s.get("config_generation")
                    for rid, s in _replica_stats(reg).items()}
            assert gens and set(gens.values()) == {0}, gens
            assert sup.spec.config_gen == 0
            events = [json.loads(l) for l in open(path)]
            assert events[-1]["e"] == "end"
            # a second recover() sees the end event and is a no-op
            assert ctl.recover() is None
        finally:
            rt.stop()
            sup.stop()
