"""Tests for the native (C++) control-plane runtime.

Covers the subsystems the reference tests through its C++ core under
mpirun (SURVEY.md §4): negotiation/ordering, tensor fusion, the response
cache fast path, coordinator-detected mismatch errors, Join accounting,
the stall inspector, the timeline writer, and clean shutdown.  Single
process tests run against the session runtime (size=1 controller);
multi-process tests spawn two real processes through the launcher.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import eager_runtime, native
from horovod_tpu.runner import launch
from horovod_tpu.runner.hosts import HostSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "native_worker.py")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestNativeBuild:
    def test_library_builds_and_loads(self):
        assert native.native_built(), native.build_error()

    def test_dtype_mapping(self):
        assert native.dtype_enum(np.dtype("float32")) == 7
        assert native.dtype_name(10) == "bfloat16"
        with pytest.raises(TypeError):
            native.dtype_enum("complex64")


class TestSingleProcessRuntime:
    """The session fixture starts the native runtime with size=1: the full
    enqueue -> negotiate -> fuse -> execute pipeline minus sockets."""

    def test_runtime_active(self, hvd):
        rt = eager_runtime.get()
        assert rt is not None, native.build_error()
        assert rt.cycles() > 0

    def test_sync_ops_through_native(self, hvd):
        rt = eager_runtime.get()
        before = rt.cycles()
        out = hvd.allreduce(np.arange(6, dtype=np.float32), hvd.Sum,
                            name="nat.t1")
        # Chip-weighted Sum: the submission stands for every local chip.
        np.testing.assert_allclose(
            out, hvd.local_size() * np.arange(6, dtype=np.float32))
        assert rt.cycles() > before

    def test_fused_async_group(self, hvd):
        hs = [
            hvd.allreduce_async(np.full((5,), float(i)), hvd.Sum,
                                name=f"nat.fuse.{i}")
            for i in range(4)
        ]
        for i, h in enumerate(hs):
            np.testing.assert_allclose(
                hvd.synchronize(h),
                np.full((5,), float(i * hvd.local_size())))

    def test_duplicate_name_rejected(self, hvd):
        h = hvd.allreduce_async(np.ones(3), hvd.Sum, name="nat.dup")
        with pytest.raises(eager_runtime.CollectiveError,
                           match="duplicate|already"):
            hvd.allreduce_async(np.ones(3), hvd.Sum, name="nat.dup")
        hvd.synchronize(h)

    def test_cache_populates_and_hits(self, hvd):
        rt = eager_runtime.get()
        entries_before = rt.cache_entries()
        for _ in range(4):
            hvd.allreduce(np.ones(2, np.float32), hvd.Sum, name="nat.cached")
        assert rt.cache_entries() > entries_before or rt.cache_hits() > 0

    def test_poll_eventually_true(self, hvd):
        h = hvd.allreduce_async(np.ones(4), hvd.Average, name="nat.poll")
        import time

        deadline = time.time() + 10
        while not hvd.poll(h):
            assert time.time() < deadline
            time.sleep(0.001)
        np.testing.assert_allclose(hvd.synchronize(h), np.ones(4))

    def test_barrier(self, hvd):
        hvd.barrier()  # size=1: completes via the BARRIER response path

    def test_mixed_dtypes_separate_buckets(self, hvd):
        a = hvd.allreduce_async(np.ones(3, np.float32), hvd.Sum, name="nat.f32")
        b = hvd.allreduce_async(np.ones(3, np.int32), hvd.Sum, name="nat.i32")
        ra, rb = hvd.synchronize(a), hvd.synchronize(b)
        assert ra.dtype == np.float32 and rb.dtype == np.int32


class TestResponseWire:
    def test_parse_roundtrip_via_executor(self, hvd):
        """The executor's parsed Response must faithfully carry names,
        shapes and scales — checked by a prescaled op end-to-end."""
        out = hvd.allreduce(np.full((2, 3), 2.0, np.float32), hvd.Sum,
                            name="nat.scaled", prescale_factor=0.5,
                            postscale_factor=4.0)
        np.testing.assert_allclose(
            out, np.full((2, 3), 4.0 * hvd.local_size()))


def _spawn_workers(tmp_path, scenario, extra_env=None, nproc=2):
    out = tmp_path / "out"
    env = {
        "PATH": os.environ.get("PATH", ""),
        "REPO": REPO,
        "PALLAS_AXON_POOL_IPS": "",  # keep subprocesses off the TPU
        "HOROVOD_NUM_PROC": str(nproc),
        "HOROVOD_JAX_PORT": str(_free_port()),
        "HOROVOD_NATIVE_PORT": str(_free_port()),
        "HOROVOD_CYCLE_TIME": "1",
    }
    env.update(extra_env or {})
    rc = launch.launch_job(
        [sys.executable, WORKER, scenario],
        [HostSpec("localhost", 1)] * nproc,
        env=env,
        output_filename=str(out),
    )
    return rc, out


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
class TestMultiProcess:
    @pytest.mark.slow
    def test_two_process_full_protocol(self, tmp_path):
        rc, out = _spawn_workers(tmp_path, "full")
        r0 = (out / "rank.0.stdout").read_text()
        r1 = (out / "rank.1.stdout").read_text()
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / "rank.1.stderr").read_text()
        assert "NATIVE-WORKER-OK rank=0" in r0
        assert "NATIVE-WORKER-OK rank=1" in r1

    def test_worker_count_seam_two_chips_per_process(self, tmp_path):
        """2 processes x 2 virtual chips each: eager Sum/Average must be
        CHIP-level (weight per-process contributions by local_size,
        divide Average by size()) and match the in-graph collectives —
        the eager/in-graph worker-count seam."""
        rc, out = _spawn_workers(tmp_path, "localsize")
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / "rank.1.stderr").read_text()
        for r in (0, 1):
            assert "NATIVE-WORKER-OK" in (out / f"rank.{r}.stdout").read_text()

    @pytest.mark.slow
    def test_wrong_secret_key_rejected(self, tmp_path):
        """The control-plane sockets perform a mutual HMAC challenge keyed
        by the job's HOROVOD_SECRET_KEY (the trust model the rendezvous KV
        already uses — reference run/common/util/secret.py): a client with
        the wrong key must be refused, and must itself refuse the
        coordinator before trusting any negotiation state."""
        port = _free_port()
        script = (
            "import sys\n"
            "from horovod_tpu import native\n"
            "rt = native.NativeRuntime()\n"
            "rank = int(sys.argv[1])\n"
            "try:\n"
            f"    rt.init(rank, 2, '127.0.0.1', {port},"
            " connect_timeout_sec=15.0)\n"
            "except RuntimeError as e:\n"
            "    print(f'INIT-FAILED rank={rank}: {e}')\n"
            "    sys.exit(3)\n"
            "print(f'INIT-OK rank={rank}')\n"
            "rt.shutdown()\n"
        )
        env = {
            "PATH": os.environ.get("PATH", ""),
            "PYTHONPATH": REPO,
            "PALLAS_AXON_POOL_IPS": "",
        }
        coord = subprocess.Popen(
            [sys.executable, "-c", script, "0"],
            env={**env, "HOROVOD_SECRET_KEY": "a" * 32},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        intruder = subprocess.run(
            [sys.executable, "-c", script, "1"],
            env={**env, "HOROVOD_SECRET_KEY": "b" * 32},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=120)
        # The wrong-key client detects the mismatch ITSELF (mutual auth)
        # and refuses to join.
        assert intruder.returncode == 3, intruder.stdout
        assert "HMAC challenge" in intruder.stdout, intruder.stdout
        # The coordinator never accepted it as rank 1: with nobody else
        # dialing in, bootstrap times out instead of proceeding with an
        # impostor.
        out, _ = coord.communicate(timeout=120)
        assert coord.returncode == 3, out
        assert "timed out waiting for" in out, out

    def test_same_secret_key_accepted(self, tmp_path):
        """Positive control for the HMAC handshake: both sides holding the
        job secret bootstrap normally (every launcher-spawned test also
        covers this — the launcher always exports HOROVOD_SECRET_KEY)."""
        port = _free_port()
        script = (
            "import sys\n"
            "from horovod_tpu import native\n"
            "rt = native.NativeRuntime()\n"
            "rank = int(sys.argv[1])\n"
            f"rt.init(rank, 2, '127.0.0.1', {port},"
            " connect_timeout_sec=60.0)\n"
            "print(f'INIT-OK rank={rank}')\n"
            "rt.shutdown()\n"
        )
        env = {
            "PATH": os.environ.get("PATH", ""),
            "PYTHONPATH": REPO,
            "PALLAS_AXON_POOL_IPS": "",
            "HOROVOD_SECRET_KEY": "c" * 32,
        }
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, str(r)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for r in (0, 1)
        ]
        for p in procs:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, out
            assert "INIT-OK" in out, out

    def test_stall_inspector_warns(self, tmp_path):
        rc, out = _spawn_workers(
            tmp_path, "stall",
            extra_env={"HOROVOD_STALL_CHECK_TIME_SECONDS": "1"})
        assert rc == 0
        stderr0 = (out / "rank.0.stderr").read_text()
        assert "missing ranks [1]" in stderr0, stderr0
        assert "stalled.t" in stderr0


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
class TestTimelineNative:
    def test_timeline_json_written(self, tmp_path):
        """Run a small single-process job with HOROVOD_TIMELINE set and
        validate the chrome-tracing output (role of the reference's
        test_timeline.py)."""
        tl = tmp_path / "timeline.json"
        script = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import numpy as np, horovod_tpu as hvd\n"
            "hvd.init()\n"
            "for i in range(3):\n"
            "    hvd.allreduce(np.ones(4, np.float32), hvd.Sum, name='tl.t')\n"
            "hvd.shutdown()\n"
        )
        env = dict(os.environ)
        env.update({
            "HOROVOD_TIMELINE": str(tl),
            "HOROVOD_TIMELINE_MARK_CYCLES": "1",
            "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": REPO,
        })
        subprocess.run([sys.executable, "-c", script], cwd=REPO, env=env,
                       check=True, timeout=180)
        events = json.loads(tl.read_text())
        names = {e.get("name") for e in events}
        assert "NEGOTIATE" in names and "EXECUTE" in names
        assert "CYCLE" in names
        # thread metadata labels the tensor lane
        assert any(e.get("ph") == "M" and
                   e.get("args", {}).get("name") == "tl.t" for e in events)
