"""Flash attention (Pallas, interpreted on CPU) and ring attention
(sequence parallelism over the 8-device mesh) tests.

The reference has no attention ops (SURVEY.md §5.7) — these cover the
TPU-native long-context extensions.  Oracle: O(S^2) reference_attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import spmd
from horovod_tpu.ops import attention as A

N = 8


def _qkv(b=2, h=2, s=128, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = A.flash_attention(q, k, v, causal, None, 64, 64)
        ref = A.reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = _qkv(s=64)
        out = A.flash_attention(q, k, v, False, None, 64, 64)
        ref = A.reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_many_blocks_long_seq(self):
        q, k, v = _qkv(b=1, h=1, s=512, d=32)
        out = A.flash_attention(q, k, v, True, None, 64, 128)
        ref = A.reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_scale_override(self):
        q, k, v = _qkv(s=64)
        out = A.flash_attention(q, k, v, False, 0.5, 64, 64)
        ref = A.reference_attention(q, k, v, sm_scale=0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_fallback_untileable(self):
        # S=100 doesn't tile by 64: silently uses the XLA reference path.
        q, k, v = _qkv(s=100, d=20)
        out = A.flash_attention(q, k, v, True, None, 64, 64)
        ref = A.reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(s=128)

        def loss_flash(q, k, v):
            return jnp.sum(A.flash_attention(q, k, v, causal, None, 64, 64) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(A.reference_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4, err_msg=name)

    def test_grad_under_jit(self):
        q, k, v = _qkv(s=64)
        g = jax.jit(jax.grad(
            lambda q: jnp.sum(A.flash_attention(q, k, v, True, None, 64, 64))
        ))(q)
        assert np.isfinite(np.asarray(g)).all()


class TestFlashWithLse:
    def test_outputs_and_both_cotangents(self):
        """(o, lse) forward matches the reference, and gradients through
        BOTH outputs (the dlse term: delta -= dlse) are exact."""
        q, k, v = _qkv(b=1, h=2, s=128, d=32)

        def loss_flash(q, k, v):
            o, lse = A.flash_attention_with_lse(q, k, v, True)
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse ** 2)

        def loss_ref(q, k, v):
            o, lse = A._reference_attention_lse(
                q, k, v, 0, A._sm_scale(q, None))
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(lse ** 2)

        o, lse = jax.jit(
            lambda q, k, v: A.flash_attention_with_lse(q, k, v, True)
        )(q, k, v)
        o_r, lse_r = A._reference_attention_lse(
            q, k, v, 0, A._sm_scale(q, None))
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_r),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_r),
                                   atol=2e-4, rtol=2e-4)
        g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, rtol=2e-3, err_msg=name)


class TestFlashShifted:
    """The runtime shifted-causal mask: one kernel serves every ring chunk
    kind (full / diagonal-causal / dead) via an SMEM int32 shift."""

    @pytest.mark.parametrize("shift", [-128, -64, 0, 64])
    def test_matches_reference_shift(self, shift):
        q, k, v = _qkv(b=1, h=2, s=128, d=32)
        o, lse = jax.jit(
            lambda q, k, v, s: A.flash_attention_shifted(q, k, v, s,
                                                         None, 64, 64)
        )(q, k, v, jnp.int32(shift))
        o_r, lse_r = A._reference_attention_lse(
            q, k, v, shift, A._sm_scale(q, None))
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_r),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_r),
                                   atol=2e-4, rtol=2e-4)

    def test_dead_chunk_yields_zero_and_neg_inf(self):
        """shift >= S masks everything: o == 0, lse == NEG_INF, so the
        chunk vanishes under a logsumexp merge."""
        q, k, v = _qkv(b=1, h=1, s=64, d=16)
        o, lse = A.flash_attention_shifted(q, k, v, jnp.int32(64),
                                           None, 64, 64)
        np.testing.assert_array_equal(np.asarray(o), 0.0)
        assert (np.asarray(lse) <= A.NEG_INF / 2).all()

    def test_gradients_match_reference_shift(self):
        q, k, v = _qkv(b=1, h=1, s=128, d=16)
        shift = jnp.int32(-64)  # half-window: exercises partial masking

        def loss_flash(q, k, v):
            o, lse = A.flash_attention_shifted(q, k, v, shift, None, 64, 64)
            return jnp.sum(o ** 2) + jnp.sum(lse ** 2)

        def loss_ref(q, k, v):
            o, lse = A._reference_attention_lse(
                q, k, v, shift, A._sm_scale(q, None))
            return jnp.sum(o ** 2) + jnp.sum(lse ** 2)

        g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, rtol=2e-3, err_msg=name)


class TestRingAttention:
    def _run_ring(self, q, k, v, causal, impl="flash"):
        """q/k/v are (B, H, S_total, D); shard the sequence over the mesh."""
        B, H, S, D = q.shape

        def inner(qs, ks, vs):
            return A.ring_attention(
                qs, ks, vs, axis_name=hvd.AXIS, causal=causal, impl=impl)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        return jax.jit(f)(q, k, v)

    @pytest.mark.parametrize("impl", ["flash", "reference"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal, impl):
        q, k, v = _qkv(b=1, h=2, s=N * 16, d=32)
        out = self._run_ring(q, k, v, causal, impl)
        ref = A.reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_differentiable(self):
        # Slow (PR 17 budget pass): grad-of-sharded-ring compiles
        # ~10 s; the forward-match params above stay tier-1, and the
        # zigzag/ring-GQA gradient drills already run under -m slow.
        q, k, v = _qkv(b=1, h=1, s=N * 8, d=16)

        def loss(q, k, v):
            def inner(qs, ks, vs):
                return A.ring_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                        causal=True)
            f = spmd.shard(
                inner,
                in_specs=(P(None, None, hvd.AXIS, None),) * 3,
                out_specs=P(None, None, hvd.AXIS, None),
            )
            return jnp.sum(f(q, k, v) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(A.reference_attention(q, k, v, causal=True) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3, err_msg=name)

    def test_lse_merge_handles_masked_chunks(self):
        """Causal ring: the first shard receives only future chunks from
        others — their contributions must vanish, not NaN."""
        q, k, v = _qkv(b=1, h=1, s=N * 4, d=16)
        out = self._run_ring(q, k, v, True)
        assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
class TestZigzagRingAttention:
    """Causal ring with the zigzag chunk layout (device i holds global
    chunks (i, 2P-1-i)): must equal full causal attention after
    unpermuting, with gradients, incl. GQA shards."""

    def _zigzag(self, x, perm):
        return x[:, :, perm]

    def _run(self, q, k, v, impl="flash"):
        B, H, S, D = q.shape
        perm, inv = A.zigzag_perm(S, N)
        qz, kz, vz = (self._zigzag(t, perm) for t in (q, k, v))

        def inner(qs, ks, vs):
            return A.zigzag_ring_attention(
                qs, ks, vs, axis_name=hvd.AXIS, impl=impl)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        return jax.jit(f)(qz, kz, vz)[:, :, inv]

    @pytest.mark.parametrize("impl", ["flash", "reference"])
    def test_matches_full_causal_attention(self, impl):
        q, k, v = _qkv(b=1, h=2, s=N * 16, d=32)
        out = self._run(q, k, v, impl)
        ref = A.reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_differentiable(self):
        q, k, v = _qkv(b=1, h=1, s=N * 8, d=16)
        perm, inv = A.zigzag_perm(q.shape[2], N)

        def loss(q, k, v):
            qz, kz, vz = (t[:, :, perm] for t in (q, k, v))

            def inner(qs, ks, vs):
                return A.zigzag_ring_attention(qs, ks, vs,
                                               axis_name=hvd.AXIS)

            f = spmd.shard(
                inner,
                in_specs=(P(None, None, hvd.AXIS, None),) * 3,
                out_specs=P(None, None, hvd.AXIS, None),
            )
            return jnp.sum(f(qz, kz, vz) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(A.reference_attention(q, k, v, causal=True) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3, err_msg=name)

    def test_gqa_matches_full(self):
        H, H_kv = 4, 2
        q, _, _ = _qkv(b=1, h=H, s=N * 8, d=16)
        _, k, v = _qkv(b=1, h=H_kv, s=N * 8, d=16)
        out = self._run(q, k, v)
        ref = A.reference_attention(
            q, A.expand_kv(k, H), A.expand_kv(v, H), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_perm_inverse_roundtrip(self):
        perm, inv = A.zigzag_perm(32, 4)
        np.testing.assert_array_equal(perm[inv], np.arange(32))
        # Device i's block = global chunks (i, 2P-1-i).
        Sc = 32 // 8
        blk0 = perm[:2 * Sc]
        np.testing.assert_array_equal(
            blk0, np.concatenate([np.arange(0, Sc), np.arange(28, 32)]))

    def test_odd_shard_raises(self):
        with pytest.raises(ValueError, match="divide"):
            A.zigzag_perm(30, 4)

    def test_model_ring_zigzag_matches_unsharded(self):
        """Flagship model with attention_impl='ring_zigzag' over sp=8:
        loss and every parameter gradient match the single-device
        reference model (batch columns permuted by zigzag_perm; the
        token/target pairing and the mean are permutation-invariant,
        RoPE uses the explicit global positions)."""
        import dataclasses

        from jax.sharding import Mesh

        from horovod_tpu.models import transformer as T

        S = 64
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=S, dtype=jnp.float32, n_kv_heads=2,
            attention_impl="ring_zigzag")
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(1, cfg, batch=2, seq=S)
        perm, _ = A.zigzag_perm(S, N)
        zbatch = {k: v[:, perm] for k, v in batch.items()}

        mesh = Mesh(np.array(jax.devices()[:N]), axis_names=("sp",))

        def inner(pr, b):
            loss, grads = jax.value_and_grad(
                lambda p: T.loss_fn(p, b, cfg))(pr)
            return (jax.lax.pmean(loss, "sp"),
                    jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, "sp"), grads))

        loss_z, grads_z = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P(None, "sp")),
            out_specs=(P(), P()), check_vma=False))(params, zbatch)

        rcfg = dataclasses.replace(cfg, attention_impl="reference")
        loss_r, grads_r = jax.value_and_grad(
            lambda p: T.loss_fn(p, batch, rcfg))(params)
        np.testing.assert_allclose(float(loss_z), float(loss_r),
                                   rtol=1e-5)
        flat_z = dict(jax.tree_util.tree_leaves_with_path(grads_z))
        for path, ref in jax.tree_util.tree_leaves_with_path(grads_r):
            np.testing.assert_allclose(
                np.asarray(flat_z[path]), np.asarray(ref),
                atol=2e-4, rtol=2e-4, err_msg=jax.tree_util.keystr(path))


class TestGroupedQueryAttention:
    """GQA: K/V carry fewer heads; kernels see jnp.repeat-expanded heads
    (whose VJP is the per-group sum), and the ring rotates the SMALL
    shards.  Oracle: reference attention on manually repeated K/V."""

    def _qkv_gqa(self, h=4, h_kv=2, s=64, d=16):
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (1, h, s, d), jnp.float32)
        k = jax.random.normal(ks[1], (1, h_kv, s, d), jnp.float32)
        v = jax.random.normal(ks[2], (1, h_kv, s, d), jnp.float32)
        return q, k, v

    def test_expand_matches_manual_repeat(self, ):
        q, k, v = self._qkv_gqa()
        out = A.flash_attention(q, A.expand_kv(k, 4), A.expand_kv(v, 4),
                                True, None, 64, 64)
        ref = A.reference_attention(
            q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
            causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa_gradients_group_sum(self):
        """d/dk of the GQA attention == the group-sum of the MHA grads —
        the repeat VJP must deliver exact shared-head gradients."""
        q, k, v = self._qkv_gqa()

        def loss_gqa(k):
            o = A.flash_attention(q, A.expand_kv(k, 4), A.expand_kv(v, 4),
                                  True, None, 64, 64)
            return jnp.sum(o ** 2)

        def loss_ref(k):
            o = A.reference_attention(
                q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
                causal=True)
            return jnp.sum(o ** 2)

        g = jax.grad(loss_gqa)(k)
        gr = jax.grad(loss_ref)(k)
        assert g.shape == k.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=2e-3, rtol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_gqa_matches_full(self, causal):
        """Ring attention with H_kv=2 < H=4: the small shards rotate, the
        merged output must equal full attention on repeated K/V."""
        q, k, v = self._qkv_gqa(s=N * 8)

        def inner(qs, ks, vs):
            return A.ring_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                    causal=causal)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        out = jax.jit(f)(q, k, v)
        ref = A.reference_attention(
            q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
            causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("h,h_kv", [
        (8, 2),   # h_kv doesn't divide the 8-device axis: pre-expand path
        (16, 8),  # h_kv divides the axis: reshard-small-then-expand path
    ])
    def test_ulysses_gqa_matches_full(self, h, h_kv):
        q, k, v = self._qkv_gqa(h=h, h_kv=h_kv, s=N * 8)
        g = h // h_kv

        def inner(qs, ks, vs):
            return A.ulysses_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                       causal=True)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        out = jax.jit(f)(q, k, v)
        ref = A.reference_attention(
            q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1),
            causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_ring_gqa_gradients(self):
        """The diff's central gradient claim: the repeat VJP (group-sum)
        composed with the transposed ppermute ring must deliver exact
        shared-KV-head gradients vs the repeated-K/V full-attention
        oracle."""
        q, k, v = self._qkv_gqa(h=4, h_kv=2, s=N * 4)

        def loss_ring(q, k, v):
            def inner(qs, ks, vs):
                return A.ring_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                        causal=True)
            f = spmd.shard(
                inner,
                in_specs=(P(None, None, hvd.AXIS, None),) * 3,
                out_specs=P(None, None, hvd.AXIS, None),
            )
            return jnp.sum(f(q, k, v) ** 2)

        def loss_ref(q, k, v):
            o = A.reference_attention(
                q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
                causal=True)
            return jnp.sum(o ** 2)

        g = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            assert a.shape == b.shape, name  # kv grads stay H_kv-shaped
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, rtol=2e-3, err_msg=name)

    def test_bad_group(self):
        with pytest.raises(ValueError, match="multiple"):
            A.expand_kv(jnp.zeros((1, 3, 8, 4)), 4)

    def test_ring_gqa_permutes_small_shards(self):
        """The central GQA traffic claim, checked at the HLO level: the
        ring's collective-permute must move the UNEXPANDED (H_kv-wide)
        K/V shards, not the repeated full-head tensors."""
        h, h_kv, s, d = 4, 2, N * 8, 16
        q = jnp.zeros((1, h, s // N, d), jnp.float32)
        kv = jnp.zeros((1, h_kv, s // N, d), jnp.float32)

        def inner(qs, ks, vs):
            return A.ring_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                    causal=True)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        # Per-shard shapes inside shard_map: K/V are (1, h_kv, s/N, d).
        hlo = jax.jit(f).lower(
            jnp.zeros((1, h, s, d), jnp.float32),
            jnp.zeros((1, h_kv, s, d), jnp.float32),
            jnp.zeros((1, h_kv, s, d), jnp.float32),
        ).compile().as_text()
        small = f"f32[1,{h_kv},{s // N},{d}]"
        big = f"f32[1,{h},{s // N},{d}]"
        permutes = [l for l in hlo.splitlines() if "collective-permute" in l
                    and "start" not in l]
        assert permutes, "ring must emit collective-permutes"
        assert all(small in l for l in permutes), permutes[:2]
        assert not any(big in l for l in permutes), (
            "ppermute must carry the unexpanded H_kv shards", permutes[:2])


class TestUlyssesAttention:
    def _run(self, q, k, v, causal, impl="reference"):
        def inner(qs, ks, vs):
            return A.ulysses_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                       causal=causal, impl=impl)

        f = spmd.shard(
            inner,
            in_specs=(P(None, None, hvd.AXIS, None),) * 3,
            out_specs=P(None, None, hvd.AXIS, None),
        )
        return jax.jit(f)(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        # heads divisible by the 8-device axis
        q, k, v = _qkv(b=1, h=N, s=N * 8, d=32)
        out = self._run(q, k, v, causal)
        ref = A.reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_flash_inner_matches(self):
        q, k, v = _qkv(b=1, h=N, s=N * 16, d=32)
        out = self._run(q, k, v, True, impl="flash")
        ref = A.reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)

    def test_differentiable(self):
        q, k, v = _qkv(b=1, h=N, s=N * 4, d=16)

        def loss(q, k, v):
            def inner(qs, ks, vs):
                return A.ulysses_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                           causal=True)
            f = spmd.shard(
                inner,
                in_specs=(P(None, None, hvd.AXIS, None),) * 3,
                out_specs=P(None, None, hvd.AXIS, None),
            )
            return jnp.sum(f(q, k, v) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(A.reference_attention(q, k, v, causal=True) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-3, err_msg=name)

    def test_indivisible_heads_raise(self):
        q, k, v = _qkv(b=1, h=3, s=N * 2, d=16)
        with pytest.raises(Exception, match="divisible|ring_attention"):
            self._run(q, k, v, False)

    def test_flash_inner_differentiable_under_shard_map(self):
        """The Pallas custom-vjp kernels must transpose correctly inside
        shard_map (the ulysses production path)."""
        q, k, v = _qkv(b=1, h=N, s=N * 16, d=32)

        def loss(q, k, v):
            def inner(qs, ks, vs):
                return A.ulysses_attention(qs, ks, vs, axis_name=hvd.AXIS,
                                           causal=True, impl="flash")
            f = spmd.shard(
                inner,
                in_specs=(P(None, None, hvd.AXIS, None),) * 3,
                out_specs=P(None, None, hvd.AXIS, None),
            )
            return jnp.sum(f(q, k, v) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(A.reference_attention(q, k, v, causal=True) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3, rtol=2e-3, err_msg=name)


class TestTransformerIntegration:
    """attention_impl config: flash and ring must match the reference
    implementation through the full model forward."""

    def _cfg(self, impl, dtype=jnp.float32):
        from horovod_tpu.models import transformer as T

        return T.TransformerConfig(
            vocab_size=64, d_model=64, n_heads=2, n_layers=2, d_ff=128,
            max_seq=64, dtype=dtype, attention_impl=impl)

    def test_flash_matches_reference_forward(self):
        from horovod_tpu.models import transformer as T

        cfg_ref = self._cfg("reference")
        cfg_fl = self._cfg("flash")
        params = T.init_params(jax.random.PRNGKey(0), cfg_ref)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)
        ref = T.forward(params, tokens, cfg_ref)
        fl = T.forward(params, tokens, cfg_fl)
        np.testing.assert_allclose(np.asarray(fl), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_ring_matches_reference_forward(self):
        """Sequence-parallel forward over the sp axis == full-sequence
        reference forward."""
        from horovod_tpu.models import transformer as T
        from jax.sharding import Mesh

        cfg_ref = self._cfg("reference")
        cfg_ring = self._cfg("ring")
        params = T.init_params(jax.random.PRNGKey(0), cfg_ref)
        S = 64
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
        ref = T.forward(params, tokens, cfg_ref)

        mesh = Mesh(np.array(jax.devices()[:N]), axis_names=("sp",))

        def inner(params, tokens):
            return T.forward(params, tokens, cfg_ring)

        # check_vma=False: the production wrapper (spmd.shard) disables
        # vma tracking too — the Pallas CPU interpreter can't slice
        # varying-over-axis operands (jax suggests this exact workaround).
        f = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        ))
        out = f(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_ring_gqa_matches_reference_forward(self):
        """GQA model (n_kv_heads=1 < n_heads=2): ring over sp ==
        full-sequence reference, both running the grouped projections."""
        import dataclasses

        from horovod_tpu.models import transformer as T
        from jax.sharding import Mesh

        cfg_ref = dataclasses.replace(self._cfg("reference"), n_kv_heads=1)
        cfg_ring = dataclasses.replace(cfg_ref, attention_impl="ring")
        params = T.init_params(jax.random.PRNGKey(0), cfg_ref)
        assert params["layers"]["wk"].shape[2] == 1  # grouped projection
        S = 64
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
        ref = T.forward(params, tokens, cfg_ref)

        mesh = Mesh(np.array(jax.devices()[:N]), axis_names=("sp",))

        def inner(params, tokens):
            return T.forward(params, tokens, cfg_ring)

        f = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        ))
        out = f(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_ulysses_matches_reference_forward(self):
        """alltoall sequence-parallel forward over sp == full-sequence
        reference forward (needs heads % sp == 0)."""
        import dataclasses

        from horovod_tpu.models import transformer as T
        from jax.sharding import Mesh

        cfg_ref = dataclasses.replace(self._cfg("reference"), n_heads=N)
        cfg_uly = dataclasses.replace(cfg_ref, attention_impl="ulysses")
        params = T.init_params(jax.random.PRNGKey(0), cfg_ref)
        S = 64
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
        ref = T.forward(params, tokens, cfg_ref)

        mesh = Mesh(np.array(jax.devices()[:N]), axis_names=("sp",))

        def inner(params, tokens):
            return T.forward(params, tokens, cfg_uly)

        f = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,  # Pallas CPU interpreter vs varying operands
        ))
        out = f(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)
