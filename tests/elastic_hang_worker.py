"""Heartbeat-monitoring worker: publishes KV heartbeats like the
WorkerNotificationManager, and ELASTIC_HANG_RANK (epoch 0 only) stops
heartbeating while staying alive — the only failure mode exit-code
monitoring cannot see.  Deliberately JAX-free so the heartbeat test
stays fast."""

import os
import sys
import time

sys.path.insert(0, os.environ["REPO"])

from horovod_tpu.elastic.worker import KV_SCOPE, heartbeat_key  # noqa: E402
from horovod_tpu.runner.rendezvous import KVClient  # noqa: E402

rank = int(os.environ["HOROVOD_RANK"])
epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))
hang_rank = int(os.environ.get("ELASTIC_HANG_RANK", "-1"))
interval = float(os.environ.get("HOROVOD_ELASTIC_HEARTBEAT", "0.2"))

kv = KVClient(os.environ["HOROVOD_COORDINATOR_ADDR"],
              int(os.environ["HOROVOD_COORDINATOR_PORT"]), timeout=5.0)

hang = rank == hang_rank and epoch == 0
# Everyone heartbeats for ~1s; then the hang rank goes silent but stays
# alive (a wedged process), while the others finish cleanly.
for _ in range(max(2, int(1.0 / interval))):
    kv.put(KV_SCOPE, heartbeat_key(epoch, rank), repr(time.time()).encode())
    time.sleep(interval)
if hang:
    print(f"ELASTIC-HANG rank={rank}", flush=True)
    while True:  # silent forever: only stale-heartbeat detection sees this
        time.sleep(1.0)
print(f"ELASTIC-HANG-WORKER-OK rank={rank}", flush=True)
