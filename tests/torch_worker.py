"""Two-process torch-frontend worker: distributed data-parallel training
with DistributedOptimizer must keep replicas bit-identical (the
reference's core contract), plus cross-rank op checks."""

import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402

hvd.init()
rank = hvd.cross_rank()
nproc = hvd.cross_size()
assert nproc == 2

# cross-rank allreduce value check
x = torch.full((4,), float(rank + 1))
out = hvd.allreduce(x, op=hvd.Sum)
assert torch.allclose(out, torch.full((4,), 3.0)), out

# broadcast from rank 1
val = torch.full((2,), float(rank))
out = hvd.broadcast(val, 1)
assert torch.allclose(out, torch.full((2,), 1.0)), out

# allgather with different first dims
mine = torch.full((rank + 1, 2), float(rank))
out = hvd.allgather(mine)
assert out.shape == (3, 2), out.shape

# DistributedOptimizer: different seeds per rank, broadcast aligns, then
# each rank trains on DIFFERENT data; averaged gradients must keep the
# replicas identical.
torch.manual_seed(100 + rank)
model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                            torch.nn.Linear(8, 1))
hvd.broadcast_parameters(model.state_dict(), root_rank=0)
opt = hvd.DistributedOptimizer(
    torch.optim.SGD(model.parameters(), lr=0.05),
    named_parameters=model.named_parameters())

torch.manual_seed(rank)  # different data per rank
for step in range(5):
    xb = torch.randn(16, 4)
    yb = xb.sum(dim=1, keepdim=True)
    opt.zero_grad()
    loss = torch.nn.functional.mse_loss(model(xb), yb)
    loss.backward()
    opt.step()

# replicas must agree exactly (same averaged grads from the same start)
flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
gathered = hvd.allgather(flat.unsqueeze(0))
assert torch.allclose(gathered[0], gathered[1], atol=1e-6), \
    (gathered[0] - gathered[1]).abs().max()

# optimizer state broadcast
opt2 = torch.optim.Adam(model.parameters(), lr=1e-3)
model(torch.randn(2, 4)).sum().backward()
opt2.step()
hvd.broadcast_optimizer_state(opt2, root_rank=0)

hvd.shutdown()
print(f"TORCH-WORKER-OK rank={rank}")
