"""Two-process torch-frontend worker: distributed data-parallel training
with DistributedOptimizer must keep replicas bit-identical (the
reference's core contract), plus cross-rank op checks."""

import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402

SCENARIO = sys.argv[1] if len(sys.argv) > 1 else "full"

hvd.init()
rank = hvd.cross_rank()
nproc = hvd.cross_size()


def scenario_adasum():
    """Delta-model Adasum optimizer vs the pairwise oracle (reference
    test_adasum_* structure): local SGD update, Adasum-combined parameter
    delta, verified against adasum_reduce_stack of the gathered per-rank
    deltas.  Runs at ANY nproc (spawned at 2, 3 and 4): power-of-two
    counts run the distributed VHDD rounds, others exercise the
    gather + serial-oracle fallback."""
    from horovod_tpu.ops import adasum as AD

    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                                torch.nn.Linear(8, 1))
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    lr = 0.05
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=lr),
        named_parameters=model.named_parameters(), op=hvd.Adasum)
    # op=Adasum must select the DELTA optimizer, not gradient averaging.
    assert hasattr(opt, "_starting_models"), type(opt).__mro__

    start = [p.detach().clone() for p in model.parameters()]
    torch.manual_seed(123 + rank)  # different data per rank
    xb = torch.randn(16, 4)
    yb = xb.sum(dim=1, keepdim=True)
    opt.zero_grad()
    torch.nn.functional.mse_loss(model(xb), yb).backward()
    grads = [p.grad.detach().clone() for p in model.parameters()]
    opt.step()

    # Oracle: each rank's local delta is -lr*g (plain SGD); gather them
    # and reduce with the serial pairwise recursion.
    for i, (p, s, g) in enumerate(zip(model.parameters(), start, grads)):
        local_delta = (-lr * g).reshape(1, -1)
        all_d = hvd.allgather(local_delta, name=f"adasum.oracle.{i}")
        expect = s.reshape(-1) + torch.from_numpy(
            np.asarray(AD.adasum_reduce_stack(all_d.numpy())))
        np.testing.assert_allclose(
            p.detach().reshape(-1).numpy(), expect.numpy(),
            rtol=1e-5, atol=1e-6)

    # Replicas must be identical after the sync step.
    flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(flat.unsqueeze(0), name="adasum.flat")
    for r in range(1, nproc):
        assert torch.allclose(gathered[0], gathered[r], atol=1e-6), r

    # backward_passes_per_step=2: the first step applies only the LOCAL
    # update (replicas drift apart on different data); the second
    # Adasum-combines the cumulative drift and re-converges them.
    opt2 = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=lr),
        named_parameters=model.named_parameters(), op=hvd.Adasum,
        backward_passes_per_step=2)
    torch.manual_seed(500 + rank)
    for it in range(2):
        xb = torch.randn(16, 4)
        yb = xb.sum(dim=1, keepdim=True)
        opt2.zero_grad()
        torch.nn.functional.mse_loss(model(xb), yb).backward()
        opt2.step()
        flat = torch.cat(
            [p.detach().reshape(-1) for p in model.parameters()])
        gathered = hvd.allgather(flat.unsqueeze(0), name=f"adasum.k2.{it}")
        same = all(torch.allclose(gathered[0], gathered[r], atol=1e-7)
                   for r in range(1, nproc))
        if it == 0:
            assert not same, "ranks must drift on the non-comm step"
        else:
            assert same, "comm step must re-converge the replicas"

    # skip_synchronize is meaningless for the delta optimizer.
    try:
        with opt.skip_synchronize():
            pass
        raise SystemExit("expected AssertionError from skip_synchronize")
    except AssertionError:
        pass

    # Default naming (no named_parameters) must produce unique names for
    # every parameter, not one name per param GROUP.
    opt3 = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=lr), op=hvd.Adasum)
    assert hasattr(opt3, "_starting_models")
    opt3.zero_grad()
    xb = torch.randn(4, 4)
    torch.nn.functional.mse_loss(model(xb), xb.sum(1, keepdim=True)).backward()
    opt3.step()  # would deadlock/raise on duplicate names

    hvd.shutdown()
    print(f"TORCH-WORKER-OK rank={rank}")


if SCENARIO == "adasum":
    scenario_adasum()
    sys.exit(0)

assert nproc == 2

# cross-rank allreduce value check
x = torch.full((4,), float(rank + 1))
out = hvd.allreduce(x, op=hvd.Sum)
assert torch.allclose(out, torch.full((4,), 3.0)), out

# broadcast from rank 1
val = torch.full((2,), float(rank))
out = hvd.broadcast(val, 1)
assert torch.allclose(out, torch.full((2,), 1.0)), out

# allgather with different first dims
mine = torch.full((rank + 1, 2), float(rank))
out = hvd.allgather(mine)
assert out.shape == (3, 2), out.shape

# DistributedOptimizer: different seeds per rank, broadcast aligns, then
# each rank trains on DIFFERENT data; averaged gradients must keep the
# replicas identical.
torch.manual_seed(100 + rank)
model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                            torch.nn.Linear(8, 1))
hvd.broadcast_parameters(model.state_dict(), root_rank=0)
opt = hvd.DistributedOptimizer(
    torch.optim.SGD(model.parameters(), lr=0.05),
    named_parameters=model.named_parameters())

torch.manual_seed(rank)  # different data per rank
for step in range(5):
    xb = torch.randn(16, 4)
    yb = xb.sum(dim=1, keepdim=True)
    opt.zero_grad()
    loss = torch.nn.functional.mse_loss(model(xb), yb)
    loss.backward()
    opt.step()

# replicas must agree exactly (same averaged grads from the same start)
flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
gathered = hvd.allgather(flat.unsqueeze(0))
assert torch.allclose(gathered[0], gathered[1], atol=1e-6), \
    (gathered[0] - gathered[1]).abs().max()

# optimizer state broadcast
opt2 = torch.optim.Adam(model.parameters(), lr=1e-3)
model(torch.randn(2, 4)).sum().backward()
opt2.step()
hvd.broadcast_optimizer_state(opt2, root_rank=0)
# that backward also fired opt's hooks (they hang off the model's
# parameters) — drain the in-flight handles before the next section
opt.synchronize()

# --- synchronize() + skip_synchronize() under gradient clipping ---------
# (reference test_torch.py gradient-clipping idiom: synchronize manually,
# clip the REDUCED gradients, then step inside skip_synchronize so the
# optimizer doesn't re-reduce).
# Re-align replicas first: the opt2 section above applied UN-reduced
# local Adam grads (deliberately — it only tests state broadcast).
hvd.broadcast_parameters(model.state_dict(), root_rank=0)
xb = torch.randn(8, 4) * (rank + 1)  # different data per rank
yb = xb.sum(dim=1, keepdim=True)
opt.zero_grad()
torch.nn.functional.mse_loss(model(xb), yb).backward()
opt.synchronize()
clip_to = 1e-3
total_norm = torch.nn.utils.clip_grad_norm_(model.parameters(), clip_to)
before = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
with opt.skip_synchronize():
    opt.step()
after = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
# the applied update is the clipped gradient: ||delta|| <= lr * clip
assert (after - before).norm() <= 0.05 * clip_to * 1.01 + 1e-8, \
    (after - before).norm()
# replicas still bit-identical (clipping happened on identical reduced
# grads, skip_synchronize prevented a second reduction)
flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
gathered = hvd.allgather(flat.unsqueeze(0))
assert torch.allclose(gathered[0], gathered[1], atol=1e-6), \
    (gathered[0] - gathered[1]).abs().max()

# --- join() with uneven per-rank batch counts ---------------------------
# (reference test_horovod_join_allreduce, test_torch.py:1540+): rank 0
# exhausts its data first and joins; rank 1 keeps stepping — its
# allreduces complete against rank 0's implicit zeros — then joins too.
n_batches = 3 + 2 * rank
torch.manual_seed(1000 + rank)
for step in range(n_batches):
    xb = torch.randn(8, 4)
    yb = xb.sum(dim=1, keepdim=True)
    opt.zero_grad()
    torch.nn.functional.mse_loss(model(xb), yb).backward()
    opt.step()
hvd.join()
# replicas diverged while rank 1 trained alone; re-align from the rank
# that saw all its data (reference join examples re-broadcast after).
hvd.broadcast_parameters(model.state_dict(), root_rank=nproc - 1)
flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
gathered = hvd.allgather(flat.unsqueeze(0))
assert torch.allclose(gathered[0], gathered[1], atol=1e-6), \
    (gathered[0] - gathered[1]).abs().max()

# --- 0-d tensors across the wire (BatchNorm num_batches_tracked) -------
bn = torch.nn.BatchNorm1d(4)
bn(torch.randn(8, 4))  # num_batches_tracked becomes a 0-d int64 == 1
bn.num_batches_tracked.fill_(rank + 3)
hvd.broadcast_parameters(bn.state_dict(), root_rank=0)
assert bn.num_batches_tracked.shape == ()  # shape restored, not (1,)
assert int(bn.num_batches_tracked) == 3
scalar = hvd.allreduce(torch.tensor(float(rank)), op=hvd.Sum)
assert scalar.shape == () and float(scalar) == 1.0, scalar

# --- DataLoader sharding + lockstep across real processes --------------
from horovod_tpu.data import DataLoader  # noqa: E402

rows = np.arange(101, dtype=np.float32)
dl = DataLoader({"y": rows}, 10, shuffle=False)
# lockstep: both ranks agree on the batch count (min shard decides):
# 101 rows over 2 ranks -> shards of 51/50 -> 5 batches each.
assert len(dl) == 5, len(dl)
mine = np.concatenate([np.asarray(b["y"]) for b in dl])
assert len(mine) == 50
# disjoint: gather both ranks' rows, no overlap
import horovod_tpu as hvd_core  # noqa: E402

all_rows = hvd_core.allgather(mine[None, :], name="dl.rows")
a, b = np.asarray(all_rows)
assert not set(a.tolist()) & set(b.tolist())

hvd.shutdown()
print(f"TORCH-WORKER-OK rank={rank}")
