"""Tensor-parallel serving replicas (ISSUE 15): the compiled engine
tick under GSPMD over a ``tp`` mesh.

The gold checks:

* a tp=2 engine (forced multi-device CPU — the
  ``tests/test_gspmd_multiprocess.py`` trick, armed process-wide by
  conftest's 8 virtual devices) serves greedy AND sampled output
  TOKEN-IDENTICAL to the tp=1 oracle, with ZERO decode recompiles
  across churn — sharding is an annotation on the same executables,
  so the live set, page tables, and sampling columns stay data;
* the compiled tick really is sharded: the lowered HLO carries the
  head-gather/psum collectives XLA inserted;
* sharding edge cases are TYPED config errors at engine construction
  (head count not divisible by tp, tp without paging, tp past the
  visible device count) — never an XLA shape crash;
* bf16/int8 page pools shard cleanly (int8 scales ride the same head
  split), COW prefix register/attach works under tp, and chunked
  prefill / speculative decoding / restart-resume each compose with
  the tp mesh token-identically;
* the ``/stats`` routing contract grows typed ``tp`` + ``mesh`` keys
  and the registry surfaces them;
* (chaos drill) SIGKILL a tp=2 replica mid-stream behind the router →
  journal-resumed on a SURVIVING tp replica, byte-identical tokens,
  gapless SSE indices.
"""

import dataclasses
import http.client
import json
import os
import signal
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving import sse
from horovod_tpu.serving.sharding import (
    ServingSharding,
    ShardingConfigError,
    make_tp_mesh,
)
from horovod_tpu.serving.router import (
    ReplicaRegistry,
    ReplicaSpec,
    ReplicaSupervisor,
    RouterServer,
)

pytestmark = pytest.mark.tp


def _cfg(**kw):
    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _engine(params, cfg, tp, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", cfg.max_seq)
    kw.setdefault("max_prefills_per_tick", 2)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(tp=tp, **kw))


def _drive(eng, reqs):
    """Submit ``(prompt, max_new, kwargs)`` triples, step to
    completion, return the per-request token lists."""
    futs = [eng.submit(p, max_new_tokens=n, **kw) for p, n, kw in reqs]
    while not all(f.done() for f in futs):
        eng.step()
    return [f.result() for f in futs]


# ---------------------------------------------------------------------------
# typed configuration errors (never an XLA shape crash)
# ---------------------------------------------------------------------------


class TestTpConfig:
    def test_heads_not_divisible_is_typed(self, model):
        params, cfg = model  # n_heads=4
        with pytest.raises(ShardingConfigError, match="n_heads"):
            _engine(params, cfg, tp=3)

    def test_kv_heads_not_divisible_is_typed(self):
        cfg = _cfg(n_heads=4, n_kv_heads=1)  # MQA: 1 kv head
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ShardingConfigError, match="kv_heads"):
            _engine(params, cfg, tp=2)

    def test_tp_past_device_count_is_typed(self):
        # Heads divide by 16, the 8 forced devices (conftest) do not.
        cfg = _cfg(n_heads=16, n_kv_heads=16, d_model=64)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        with pytest.raises(ShardingConfigError, match="devices"):
            _engine(params, cfg, tp=16)

    def test_tp_requires_paged(self, model):
        params, cfg = model
        with pytest.raises(ShardingConfigError, match="paged"):
            _engine(params, cfg, tp=2, paged=False)

    def test_tp_zero_is_typed(self, model):
        params, cfg = model
        with pytest.raises(ShardingConfigError, match=">= 1"):
            _engine(params, cfg, tp=0)

    def test_mesh_helper_validates_device_list(self):
        with pytest.raises(ShardingConfigError, match="exactly"):
            make_tp_mesh(2, jax.devices()[:1])


# ---------------------------------------------------------------------------
# the sharded executable really is sharded
# ---------------------------------------------------------------------------


class TestTpCollectives:
    def test_sharded_decode_tick_emits_tp_collectives(self, model):
        """Lower the paged decode tick under the engine's exact in/out
        shardings and assert XLA inserted the tp collectives — the
        paper's negotiate/fuse/launch thread, compiled into the
        program."""
        params, cfg = model
        sh = ServingSharding(cfg, 2)
        params_tp = sh.shard_params(params)
        S, ps, n_pages, max_pages = 4, 8, 9, 6
        pool = serving.init_page_pool(cfg, S, n_pages, ps)
        pool = T.shard_kv_pool(pool, sh.mesh)
        table = jnp.zeros((S, max_pages), jnp.int32)
        active = jnp.zeros((S,), bool)
        tokens = jnp.zeros((S,), jnp.int32)
        R = sh.replicated
        poolsh = sh.pool_shardings(False)

        fn = jax.jit(
            lambda p, t, a, tb, pl: T.decode_step_paged(
                p, t, pl, tb, cfg, a),
            in_shardings=(sh.param_shardings(), R, R, R, poolsh),
            out_shardings=(R, poolsh))
        hlo = fn.lower(params_tp, tokens, active, table,
                       pool).compile().as_text()
        assert "all-reduce" in hlo or "all-gather" in hlo, (
            "tp decode tick must carry tp collectives")


# ---------------------------------------------------------------------------
# the tp=1 oracle A/Bs
# ---------------------------------------------------------------------------


MIXED_REQS = [
    ([1, 2, 3], 6, {}),
    ([5, 6], 7, {"temperature": 0.8, "top_k": 8, "seed": 1}),
    ([7, 8, 9, 10, 11], 8, {}),
    ([2], 6, {"temperature": 1.1, "top_p": 0.9, "seed": 2}),
    ([9, 9, 4], 5, {}),
    ([3, 1], 6, {"temperature": 0.9, "top_k": 4, "top_p": 0.8,
                 "seed": 3}),
]


class TestTpOracle:
    def test_mixed_churn_token_identical_zero_recompiles(self, model):
        """ACCEPTANCE: greedy AND sampled requests churning through a
        tp=2 engine produce token-identical output to the tp=1 oracle
        engine, and the decode tick never recompiles after warmup —
        sharding changed the placement, not the program."""
        params, cfg = model
        out, recompiles = {}, {}
        for tp in (1, 2):
            eng = _engine(params, cfg, tp)
            eng.warmup([4, 8])
            warm = eng.decode_compilations
            out[tp] = _drive(eng, MIXED_REQS)
            recompiles[tp] = eng.decode_compilations - warm
        assert out[2] == out[1]
        assert recompiles[2] == 0, (
            f"tp decode recompiled {recompiles[2]}x across churn")

    def test_stats_contract_grows_tp_and_mesh(self, model):
        """/stats carries typed tp (int) + mesh (str) keys — the
        routing-contract growth — and the serving_tp_degree gauge
        tracks the configured degree."""
        params, cfg = model
        eng = _engine(params, cfg, tp=2)
        snap = eng.stats()
        assert snap["tp"] == 2 and isinstance(snap["tp"], int)
        assert isinstance(snap["mesh"], str) and "tp=2" in snap["mesh"]
        assert eng.metrics.tp_degree.value == 2

        eng1 = _engine(params, cfg, tp=1)
        snap1 = eng1.stats()
        assert snap1["tp"] == 1 and snap1["mesh"] == ""
        assert eng1.metrics.tp_degree.value == 1

    def test_registry_surfaces_tp_and_mesh(self, model):
        """The registry's poll parses the new contract keys and the
        per-replica fleet view (status.as_dict, what the router's
        /stats replicas dict serves) carries them."""
        params, cfg = model
        eng = _engine(params, cfg, tp=2)
        srv = serving.ServingServer(eng, port=0).start()
        try:
            host, port = srv.address
            reg = ReplicaRegistry()
            from horovod_tpu.serving.router.registry import (
                ReplicaEndpoint,
            )
            reg.add(ReplicaEndpoint("r0g0", host, port))
            reg.poll_now()
            st = reg.statuses()[0]
            assert st.tp == 2
            assert "tp=2" in st.mesh
            d = st.as_dict()
            assert d["tp"] == 2 and "tp=2" in d["mesh"]
        finally:
            srv.stop(drain_timeout=5.0)


class TestTpKvDtypes:
    # bf16 is slow (PR 17 budget pass): int8 exercises the stricter
    # path (payload + per-vector scales both sharded) and stays
    # tier-1; each dtype's tp=1 behavior is covered in test_paged.
    @pytest.mark.parametrize(
        "kv_dtype",
        [pytest.param("bf16", marks=pytest.mark.slow), "int8"])
    def test_quantized_pools_shard_cleanly(self, model, kv_dtype):
        """bf16/int8 page pools under tp: the payload (and, for int8,
        the per-vector scales) ride the same head sharding, and output
        matches the tp=1 engine at the SAME kv_dtype (int8 is lossy vs
        f32, but deterministic — the oracle is the same-dtype tp=1
        engine)."""
        params, cfg = model
        out = {}
        for tp in (1, 2):
            eng = _engine(params, cfg, tp, kv_dtype=kv_dtype)
            eng.warmup([4])
            out[tp] = _drive(eng, MIXED_REQS[:4])
        assert out[2] == out[1]


class TestTpPrefix:
    @pytest.mark.slow
    def test_prefix_register_attach_cow_under_tp(self, model):
        # Slow (PR 17 budget pass): two engines + three sharer
        # admission shapes are ~13 s; the tp mixed-churn oracle stays
        # tier-1 and the COW ladder is covered at tp=1 in test_paged.
        """COW prefix sharing under tp: register a shared prefix (one
        prefill into head-sharded pinned pages), admit sharers that
        attach / suffix-prefill / COW-split its last page — output
        token-identical to the tp=1 engine doing the same."""
        params, cfg = model
        prefix = [7, 3, 5, 9, 2, 4, 6, 8, 1]  # 9 tokens: partial page
        reqs = [
            (prefix, 6, {}),                     # attach-only
            (prefix + [1, 2], 6, {}),            # suffix + COW split
            (prefix + [3], 5, {"temperature": 0.8, "seed": 3}),
            ([1, 2, 3], 6, {}),                  # no prefix
        ]
        out, shared = {}, {}
        for tp in (1, 2):
            eng = _engine(params, cfg, tp, page_size=4)
            eng.register_prefix(prefix)
            eng.warmup([4])
            # The registered prefix's pages really are pinned+shared.
            shared[tp] = eng.slots.pages_shared
            out[tp] = _drive(eng, reqs)
        assert out[2] == out[1]


class TestTpCompose:
    @pytest.mark.paged_kernel
    def test_fused_paged_kernel_under_tp(self, model):
        """The fused Pallas paged-attention kernel under a tp=2 mesh
        (int8 pool — the full spec set: head-sharded pages AND
        per-vector scales through ``paged_kernel_specs``): the kernel
        runs shard-locally per kv-head inside the tick's shard_map, and
        the tp=2 fused engine emits tokens identical to the tp=1
        UNFUSED int8 oracle, with zero decode recompiles across
        churn."""
        params, cfg = model
        reqs = [([3, 5, 7], 8, {}), ([11, 2], 6, {})]
        oracle = _engine(params, cfg, 1, kv_dtype="int8",
                         paged_kernel=False)
        oracle.warmup([4])
        want = _drive(oracle, reqs)

        eng = _engine(params, cfg, 2, kv_dtype="int8",
                      paged_kernel=True)
        eng.warmup([4])
        warm = eng.decode_compilations
        got = _drive(eng, reqs)
        assert got == want
        assert eng.decode_compilations - warm == 0
        assert eng.stats()["paged_kernel_engaged"] is True
        assert oracle.stats()["paged_kernel_engaged"] is False

    @pytest.mark.slow
    def test_chunked_prefill_under_tp(self, model):
        # Slow (PR 17 budget pass): oracle + tp engine pair is ~8 s;
        # the tp mixed-churn oracle and restart-resume-under-tp stay
        # tier-1, chunking itself is covered at tp=1 in test_sched.
        """Chunked ingestion through the sharded
        ``prefill_with_prefix`` executable: a tp=2 engine ingesting a
        long prompt chunk by chunk matches the tp=1 whole-prompt
        oracle, with zero decode recompiles."""
        params, cfg = model
        rng = np.random.default_rng(0)
        long_prompt = [int(x) for x in rng.integers(0, 64, 30)]
        oracle = _engine(params, cfg, 1)
        oracle.warmup([4])
        want = _drive(oracle, [(long_prompt, 8, {}), ([1, 2], 6, {})])

        eng = _engine(params, cfg, 2, prefill_chunk_tokens=8)
        eng.warmup([4])
        warm = eng.decode_compilations
        got = _drive(eng, [(long_prompt, 8, {}), ([1, 2], 6, {})])
        assert got == want
        assert eng.decode_compilations - warm == 0

    @pytest.mark.slow
    def test_speculative_under_tp(self, model):
        # Slow (PR 17 budget pass): spec tp engine + tp=1 oracle is
        # ~8 s; the tp mixed-churn oracle stays tier-1 and the verify
        # tick is covered at tp=1 in test_speculative.
        """The sharded ``decode_verify_paged`` tick: a speculative
        (n-gram draft) tp=2 engine emits byte-identical tokens to the
        plain tp=1 oracle — greedy, repetitive (high acceptance), and
        sampled (acceptance forced to 0 as data) rows alike."""
        params, cfg = model
        reqs = [([5, 6, 5, 6, 5], 8, {}), ([1, 2, 3], 6, {}),
                ([9, 9], 5, {"temperature": 1.0, "seed": 2})]
        oracle = _engine(params, cfg, 1)
        oracle.warmup([4])
        want = _drive(oracle, reqs)

        eng = _engine(params, cfg, 2, speculative=True, spec_k=3)
        eng.warmup([4])
        warm = eng.decode_compilations
        got = _drive(eng, reqs)
        assert got == want
        assert eng.decode_compilations - warm == 0

    def test_restart_resume_under_tp(self, model):
        """Durability composes: a deterministic mid-decode crash on a
        tp=2 engine restart-RESUMES its in-flight requests (fresh
        sharded pool, re-prefill of prompt+emitted through the sharded
        executables) byte-identical to the no-fault tp=1 oracle."""
        params, cfg = model
        reqs = [([3, 4, 5], 10, {}),
                ([8, 1], 8, {"temperature": 0.9, "seed": 11})]
        oracle = _engine(params, cfg, 1)
        oracle.warmup([4])
        want = _drive(oracle, reqs)

        inj = serving.FaultInjector()
        eng = _engine(params, cfg, 2, resume=True, restart_backoff=0.01,
                      faults=inj)
        eng.warmup([4])
        inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=inj.visits("decode_tick") + 3))
        got = _drive(eng, reqs)
        assert got == want
        assert eng.metrics.resumed.value >= 1


# ---------------------------------------------------------------------------
# the front tier: N tp-K replicas behind the router (chaos drill)
# ---------------------------------------------------------------------------


def _oracle(params, cfg, prompt, steps, *, temperature=0.0, top_k=0,
            top_p=0.0, seed=0):
    return np.asarray(T.sample_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg,
        rng=jax.random.PRNGKey(seed), temperature=temperature,
        top_k=top_k, top_p=top_p))[0].tolist()


def _post(host, port, body, timeout=60, headers=None):
    c = http.client.HTTPConnection(host, port, timeout=timeout)
    c.request("POST", "/generate", body=json.dumps(body).encode(),
              headers=headers or {})
    return c, c.getresponse()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.router
class TestTpFrontTierChaos:
    def test_sigkill_tp2_replica_mid_stream_resumes_on_tp_survivor(
            self, model):
        """ACCEPTANCE: SIGKILL a tp=2 replica while it streams a
        SAMPLED request.  The router reads the dead replica's journal
        post-mortem and continues on the SURVIVING tp=2 replica —
        gapless SSE indices, token sequence byte-identical to the
        per-request oracle, ``resumed: true`` on the done event.
        Mesh ownership is per-process (disjoint device sets from the
        supervisor), so failover/resume/streaming ride unchanged."""
        params, cfg = model
        spec = ReplicaSpec(seed=0, tp=2, slots=4, warm=(8,),
                           tick_timeout=30.0, drain_timeout=3.0,
                           request_timeout=90.0)
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        journal_dir = tempfile.mkdtemp(prefix="tp_chaos_")
        sup = ReplicaSupervisor(spec, 2, registry=reg,
                                unhealthy_grace=1.5,
                                shutdown_grace=2.0,
                                backoff_initial=0.1,
                                journal_dir=journal_dir)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup)
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=240), "tp replicas never ready"
            # Both replicas really are tp=2 meshes (contract keys
            # through a real subprocess poll).
            for st in reg.in_rotation():
                assert st.tp == 2 and "tp=2" in st.mesh
            host, port = rt.address
            steps = 40
            trace = "a" * 16
            kill_done = threading.Event()

            def kill_streaming_replica():
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    for h in sup.replicas():
                        try:
                            live = serving.RequestJournal.read_live(
                                sup._journal_paths[h.rid])
                        except Exception:
                            continue
                        d = live.get(trace)
                        if (d is not None and
                                5 <= len(d["emitted_tokens"])
                                <= steps - 15):
                            os.kill(h.pid, signal.SIGKILL)
                            kill_done.set()
                            return
                    time.sleep(0.01)

            killer = threading.Thread(target=kill_streaming_replica,
                                      daemon=True)
            c, r = _post(host, port,
                         {"tokens": [9, 11], "max_new_tokens": steps,
                          "temperature": 1.1, "seed": 5,
                          "timeout_ms": 90000, "stream": True},
                         timeout=120, headers={"X-Trace-Id": trace})
            assert r.status == 200
            killer.start()
            events = sse.read_stream(r)
            c.close()
            killer.join(5.0)
            assert kill_done.is_set(), \
                "the kill never landed mid-stream (request too fast?)"
            done = [p for k, p in events if k == "done"]
            assert len(done) == 1, f"expected one done event: {events}"
            done = done[0]
            want = _oracle(params, cfg, [9, 11], steps,
                           temperature=1.1, seed=5)
            idx = [p["i"] for k, p in events if k == "token"]
            toks = [p["token"] for k, p in events if k == "token"]
            assert idx == list(range(steps)), \
                "duplicated or dropped token events across the kill"
            assert toks == want
            assert done["tokens"] == want
            assert done.get("resumed") is True
            assert reg.metrics.resume_failovers.value >= 1
        finally:
            rt.stop()
            sup.stop(drain=False)
