"""Self-tuning serving (horovod_tpu/tuning/): GP/EI math, the
compile-safe knob registry, the online tuner, and journaled-trace
replay.

The load-bearing invariants:

* ORACLE SAFETY — every knob the online tuner may touch is
  admission/batching policy, so tuned output stays token-identical to
  per-request ``greedy_decode`` (the same oracle as
  tests/test_serving.py) while the tuner is actively perturbing;
* COMPILE STABILITY — tuning never triggers a mid-serving XLA
  compile: ``decode_compilations`` stays at its post-warmup value and
  every online candidate maps to an already-warmed executable shape;
* REPLAY FIDELITY — a journaled trace re-driven through a fresh
  engine reproduces the recorded tokens exactly (greedy AND
  seeded-sampled), because decode is a pure function of (sequence,
  seed).
"""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.tuning import (
    BayesianOptimizer,
    CategoricalSweep,
    GaussianProcess,
    Knob,
    KnobSpace,
    Objective,
    OnlineTuner,
    apply_settings,
    online_knob_space,
    read_trace,
    replay,
)
from horovod_tpu.tuning.replay import warm_lens

pytestmark = [pytest.mark.serving, pytest.mark.tuning]


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _engine(model, **kw):
    params, cfg = model
    defaults = dict(n_slots=4, max_len=48, max_queue_depth=64,
                    max_prefills_per_tick=2, tick_timeout=0.0)
    defaults.update(kw)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults))


class TestGaussianProcess:
    def test_interpolates_training_points(self):
        x = np.linspace(0.0, 1.0, 8).reshape(-1, 1)
        y = np.sin(3.0 * x[:, 0])
        gp = GaussianProcess()
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        assert np.allclose(mu, y, atol=1e-2)
        assert np.all(sigma < 0.1)           # confident at data ...
        _, far = gp.predict(np.array([[3.0]]))
        assert far[0] > sigma.max()          # ... not away from it

    def test_conditioning_guard_escalates_jitter(self):
        # Near-duplicate rows (repeat scores at a pinned knob) make
        # the Gram matrix numerically singular at base noise: the fit
        # must escalate jitter and succeed, not raise LinAlgError out
        # of the serving tick loop.
        x = np.linspace(0.0, 1e-6, 8).reshape(-1, 1)
        y = np.sin(np.arange(8.0))
        gp = GaussianProcess(noise=1e-18)
        gp.fit(x, y)
        assert gp.last_jitter > 1e-18        # the guard kicked in
        mu, _ = gp.predict(x[:1])
        assert np.isfinite(mu[0])

    def test_ei_finds_1d_argmax(self):
        bo = BayesianOptimizer(bounds=[(0.0, 1.0)], seed=3)
        for _ in range(12):
            x = bo.suggest()
            bo.register(x, -(x[0] - 0.3) ** 2)
        best_x, best_y = bo.best
        assert abs(best_x[0] - 0.3) < 0.12
        assert best_y > -0.015

    def test_ei_finds_2d_argmax(self):
        bo = BayesianOptimizer(bounds=[(0.0, 1.0), (0.0, 1.0)], seed=5)
        for _ in range(18):
            x = bo.suggest()
            bo.register(x, -(x[0] - 0.7) ** 2 - (x[1] - 0.2) ** 2)
        best_x, _ = bo.best
        assert abs(best_x[0] - 0.7) < 0.2
        assert abs(best_x[1] - 0.2) < 0.2

    def test_seeded_trajectories_are_deterministic(self):
        def run():
            bo = BayesianOptimizer(bounds=[(0.0, 1.0)], seed=11)
            out = []
            for _ in range(6):
                x = bo.suggest()
                bo.register(x, -(x[0] - 0.5) ** 2)
                out.append(x[0])
            return out

        assert run() == run()


class TestCategoricalSweep:
    def test_walks_all_values_and_fixes_best(self):
        sweep = CategoricalSweep(names=["a", "b"],
                                 values=[[1, 2], [True, False]])
        # values[i][0] is what's currently running: the first observe
        # scores the incumbent.
        scores = {(1, True): 1.0, (2, True): 3.0,
                  (2, False): 2.0}
        seen = []
        while not sweep.done:
            cur = sweep.current()
            seen.append((cur["a"], cur["b"]))
            sweep.observe(scores.get((cur["a"], cur["b"]), 0.0))
        assert sweep.fixed == {"a": 2, "b": True}
        # one observation per candidate value, knob by knob (the
        # chained sweep re-scores the incumbent when it moves to the
        # next knob — that repeat is by design)
        assert len(seen) == 4
        assert {a for a, _ in seen} == {1, 2}
        assert {b for _, b in seen} == {True, False}


class TestKnobSpace:
    def test_bo_knob_clamps_and_rounds(self):
        k = Knob(name="k", default=2, kind="bo", bounds=(1, 4))
        assert k.clamp(2.6) == 3
        assert k.clamp(-5) == 1
        assert k.clamp(99) == 4

    def test_sweep_knob_rejects_non_candidates(self):
        k = Knob(name="s", default=0, kind="sweep", candidates=(0, 1, 2))
        assert k.clamp(1) == 1
        assert k.clamp(7) == 0               # back to default

    def test_space_clamp_drops_unknown_keys(self):
        space = KnobSpace([Knob(name="k", default=2, kind="bo",
                                bounds=(1, 4))])
        out = space.clamp({"k": 9, "stranger": 1})
        assert out == {"k": 4}

    def test_online_space_derived_from_warmed_engine(self, model):
        engine = _engine(model, prefill_chunk_tokens=8,
                         min_prefill_bucket=4)
        engine.warmup([12])
        try:
            space = online_knob_space(engine)
            by_name = {k.name: k for k in space.knobs}
            # kmax = min(2 prefills, 4 slots) = 2: BO box is the
            # warmed admission range.
            assert by_name["max_prefills_per_tick"].bounds == (1, 2)
            # chunk knob confined to the WARMED bucket (8): every
            # candidate pads to the same compile shape.
            lo, hi = by_name["prefill_chunk_tokens"].bounds
            assert (lo, hi) == (5, 8)
            assert by_name["page_grant_ahead"].kind == "sweep"
            # settings apply at the tick boundary: config swap +
            # scheduler attribute, no new executables.
            compiles = engine.decode_compilations
            applied = apply_settings(engine, {
                "max_prefills_per_tick": 1, "prefill_chunk_tokens": 6,
                "page_grant_ahead": 1})
            assert applied == {"max_prefills_per_tick": 1,
                               "prefill_chunk_tokens": 6,
                               "page_grant_ahead": 1}
            assert engine.engine_cfg.max_prefills_per_tick == 1
            assert engine.scheduler.max_prefills_per_tick == 1
            assert engine.engine_cfg.prefill_chunk_tokens == 6
            assert engine.decode_compilations == compiles
        finally:
            engine.stop()


class TestOnlineTuner:
    @pytest.mark.slow
    def test_oracle_safe_and_compile_stable_while_tuning(self, model):
        """THE tentpole invariant: with the tuner actively perturbing
        knobs (chunked-prefill engine, mixed prompt lengths and
        classes), every request's output is still token-identical to
        the per-request oracle and no decode executable is ever
        (re)compiled.  Slow per the one-dot-cost rule (the chunked
        warmup alone is ~15 s on CPU); the tier-1 sibling is
        test_rollback_on_constraint_violation, which asserts the same
        oracle-identity + compile-stability invariants while the
        tuner perturbs an unchunked engine."""
        params, cfg = model
        engine = _engine(model, prefill_chunk_tokens=8,
                         min_prefill_bucket=4)
        engine.warmup([12])
        warm_compiles = engine.decode_compilations
        tuner = OnlineTuner.install(engine, window_ticks=3,
                                    bo_samples=2)
        rng = np.random.default_rng(2)
        futs, prompts = [], []
        for i in range(16):
            prompt = rng.integers(0, cfg.vocab_size,
                                  3 + i % 9).tolist()
            prompts.append(prompt)
            futs.append(engine.submit(
                prompt, max_new_tokens=5,
                priority="interactive" if i % 3 else "batch"))
        for _ in range(4000):
            if all(f.done() for f in futs):
                break
            engine.step()
        for prompt, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(
                params, cfg, prompt, 5)
        assert engine.decode_compilations == warm_compiles
        assert tuner._samples >= 1           # it actually tuned
        snap = engine.stats()["tuning"]
        assert snap["phase"] in ("sweep", "bo", "pinned")
        assert snap["trajectory"]
        assert engine.metrics.tuning_samples.value == snap["samples"]
        engine.stop()

    def test_rollback_on_constraint_violation(self, model):
        """An impossible TTFT SLO makes every scored window a
        violation: the tuner must roll back each sample (re-applying
        the defaults — there is no known-good yet) and count it.
        Doubles as the tier-1 oracle-safety sibling of the slow
        chunked test above: outputs stay token-identical and decode
        never recompiles while the tuner perturbs + rolls back."""
        params, cfg = model
        engine = _engine(model)
        engine.warmup([4])
        warm_compiles = engine.decode_compilations
        tuner = OnlineTuner.install(
            engine, window_ticks=3, bo_samples=2, guard_band=0.0,
            objective=Objective(ttft_slo={"interactive": 1e-9}))
        futs = [engine.submit([1 + i, 2, 3], max_new_tokens=4)
                for i in range(10)]
        for _ in range(2000):
            if all(f.done() for f in futs) and tuner._samples >= 2:
                break
            engine.step()
            if not all(f.done() for f in futs):
                continue
            futs.append(engine.submit([5, 6], max_new_tokens=4))
        for i, f in enumerate(futs[:10]):
            assert f.result(timeout=0) == _ref_greedy(
                params, cfg, [1 + i, 2, 3], 4)
        assert engine.decode_compilations == warm_compiles
        assert tuner._rollbacks >= 1
        assert engine.metrics.tuning_rollbacks.value == tuner._rollbacks
        # no constraint-satisfying sample ever existed: the tuner is
        # parked on the defaults, not on a violating setting
        assert tuner._current == tuner.space.defaults()
        engine.stop()

    def test_tuner_crash_never_takes_serving_down(self, model):
        engine = _engine(model)
        engine.warmup([4])

        class Broken:
            def on_tick(self, engine, worked):
                raise RuntimeError("tuner bug")

        engine._tuner = Broken()
        fut = engine.submit([1, 2, 3], max_new_tokens=4)
        for _ in range(300):
            if fut.done():
                break
            engine.step()
        assert fut.result(timeout=0)         # request unharmed
        assert engine._tuner is None         # broken tuner detached
        engine.stop()


def _capture(model, jp, sampled=False):
    params, cfg = model
    engine = _engine(model, journal_path=jp)
    engine.warmup([4, 12])
    rng = np.random.default_rng(4)
    futs = []
    for i in range(10):
        prompt = rng.integers(0, cfg.vocab_size, 3 + i % 9).tolist()
        kw = {}
        if sampled and i % 2:
            kw = dict(temperature=0.8, seed=40 + i)
        futs.append(engine.submit(
            prompt, max_new_tokens=5,
            priority="interactive" if i % 2 else "batch", **kw))
    for _ in range(2000):
        if all(f.done() for f in futs):
            break
        engine.step()
    outs = [f.result(timeout=0) for f in futs]
    engine.stop()
    return outs


@pytest.fixture(scope="module")
def captured(model, tmp_path_factory):
    """One journal capture (greedy + seeded-sampled mix) shared by
    every replay test — captures are the expensive part (a full
    engine warmup each)."""
    jp = str(tmp_path_factory.mktemp("tuning") / "trace.jsonl")
    outs = _capture(model, jp, sampled=True)
    return jp, outs


class TestReplay:
    def test_read_trace_keeps_ended_entries_in_arrival_order(
            self, captured):
        jp, outs = captured
        trace = read_trace(jp)
        assert len(trace) == 10
        assert all(r.ended for r in trace)
        assert sorted(len(r.emitted) for r in trace) \
            == sorted(len(o) for o in outs)
        arrivals = [r.arrival for r in trace]
        assert arrivals == sorted(arrivals)
        assert {r.priority for r in trace} == {"interactive", "batch"}

    def test_replay_is_token_identical_both_timings(self, model,
                                                    captured):
        """Greedy AND seeded-sampled requests reproduce exactly on a
        fresh engine, at original spacing and saturated (one warmed
        engine serves both timing legs)."""
        jp, _ = captured
        trace = read_trace(jp)
        assert any(r.temperature > 0 for r in trace)
        engine = _engine(model)
        engine.warmup(warm_lens(trace, engine))
        for timing in ("afap", "original"):
            report = replay(engine, trace, timing=timing, speed=100.0)
            assert report.compared == len(trace)
            assert report.token_identical == report.compared, \
                (timing, report.mismatched_ids)
            assert report.decode_recompiles == 0
            assert report.completed == len(trace)
            assert report.score > 0
        engine.stop()

    def test_pre_arrival_journal_replays_in_file_order(self, tmp_path):
        jp = str(tmp_path / "old.jsonl")
        with open(jp, "w") as f:
            f.write('{"e":"b","id":2,"prompt":[5,6],"max_new":3}\n')
            f.write('{"e":"b","id":1,"prompt":[7],"max_new":3}\n')
        trace = read_trace(jp)
        assert [r.id for r in trace] == [2, 1]
        assert all(r.arrival == 0.0 for r in trace)

    def test_replay_rejects_unknown_timing(self, model):
        with pytest.raises(ValueError):
            replay(object(), [], timing="warp")


class TestTuningEndpoint:
    def test_get_tuning_serves_snapshot(self, model):
        engine = _engine(model, autotune=True)
        assert engine._tuner is None         # not before warmup
        engine.warmup([4])
        # EngineConfig.autotune installs the tuner at the END of
        # warmup, and /stats carries its snapshot
        assert engine._tuner is not None
        assert engine.stats()["autotune"] is True
        assert "tuning" in engine.stats()
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/tuning", timeout=10) as r:
                out = json.loads(r.read())
            assert out["enabled"] is True
            assert out["phase"] in ("warmup", "sweep", "bo", "pinned")
            assert "best" in out and "space" in out
            # tuning disabled -> the endpoint says so, still 200
            engine._tuner = None
            with urllib.request.urlopen(
                    f"http://{host}:{port}/tuning", timeout=10) as r:
                assert json.loads(r.read()) == {"enabled": False}


@pytest.mark.slow
class TestOfflineTuning:
    def test_offline_bo_over_replay_runs(self, model, captured):
        """The offline backend: BO over whole replay runs, one fresh
        engine per sample — constructor-level knobs are in scope
        here (this smoke tunes the admission width)."""
        from horovod_tpu.tuning.replay import tune

        jp, _ = captured
        trace = read_trace(jp)

        built = []

        def build(settings):
            engine = _engine(
                model,
                max_prefills_per_tick=settings["max_prefills_per_tick"])
            engine.warmup(warm_lens(trace, engine))
            built.append(settings)
            return engine

        out = tune(build, trace,
                   bounds={"max_prefills_per_tick": (1, 2)},
                   samples=3, seed=0)
        assert len(built) == 3
        assert len(out["trajectory"]) == 3
        best = out["best"]
        assert best["settings"]["max_prefills_per_tick"] in (1, 2)
        assert best["report"]["token_identical"] \
            == best["report"]["compared"]

    def test_replay_gate_passes_on_committed_trace(self):
        """The perf gate holds on the committed miniature trace: the
        current serving path replays it token-identically and within
        the score tolerance (benchmarks/replay_gate.py)."""
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "replay_gate",
            os.path.join(root, "benchmarks", "replay_gate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        verdict = mod.gate()
        assert verdict["ok"], verdict
        assert verdict["token_identical"] == verdict["compared"]
        assert verdict["decode_recompiles"] == 0


class TestResetWindow:
    def test_reset_window_drops_baseline(self):
        """The supervised-restart hook (engine._recover calls this):
        dropping the window baseline means the first post-restart
        window scores post-restart counters only — never the crash's
        dead time.  Tier-1 sibling of test_chaos.py's slow
        TestTunerResetOnRecover, which proves the _recover wiring on
        a real fault-injected engine."""
        tuner = OnlineTuner(KnobSpace([
            Knob(name="k", default=2, kind="bo", bounds=(1, 4))]))
        tuner._window = object()     # an open baseline
        tuner._ticks = 17
        tuner.reset_window()
        assert tuner._window is None
        assert tuner._ticks == 0
