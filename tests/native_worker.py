"""Multi-process worker exercising the native control plane end-to-end.

Spawned by tests/test_native.py with HOROVOD_RANK/HOROVOD_NUM_PROC and
coordinator env set; mirrors the reference's test strategy of running the
same test body on every rank under a launcher (SURVEY.md §4).
Scenario selected by argv[1]: "full" (default) or "stall".
"""

import os
import sys
import time

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

SCENARIO = sys.argv[1] if len(sys.argv) > 1 else "full"
if SCENARIO == "localsize":
    # 2 chips per process: the worker-count seam scenario (size() = 2 *
    # num_processes) — must be configured before hvd.init() builds the mesh.
    from horovod_tpu._compat import set_cpu_device_count

    set_cpu_device_count(2)

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import eager_runtime  # noqa: E402

rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ.get("HOROVOD_NUM_PROC", os.environ.get("HOROVOD_SIZE")))

hvd.init()
rt = eager_runtime.get()
assert rt is not None, "native runtime must be active for this test"
assert hvd.num_processes() == size, (hvd.num_processes(), size)


def scenario_stall():
    """Rank 0 submits a tensor rank 1 never does; the coordinator's stall
    inspector must warn (on rank 0's stderr) within the configured bound."""
    import time

    if rank == 0:
        hvd.allreduce_async(np.ones(3, np.float32), hvd.Sum, name="stalled.t")
    time.sleep(3.0)
    # Both ranks still healthy for matching traffic afterwards.
    out = hvd.allreduce(np.ones(2, np.float32), hvd.Sum, name="ok.t")
    np.testing.assert_allclose(out, np.full(2, float(size)))
    hvd.shutdown()
    print(f"NATIVE-WORKER-OK rank={rank}")


def scenario_full():
    x = np.full((4,), float(rank + 1), np.float32)
    total = sum(r + 1 for r in range(size))

    # sync allreduce: Sum and Average
    np.testing.assert_allclose(
        hvd.allreduce(x, hvd.Sum, name="t.sum"), np.full((4,), total))
    np.testing.assert_allclose(
        hvd.allreduce(x, hvd.Average, name="t.avg"),
        np.full((4,), total / size))

    # async group submitted together -> fused by the controller
    hs = [
        hvd.allreduce_async(
            np.full((8,), float(i + rank), np.float32), hvd.Sum, name=f"g.{i}")
        for i in range(6)
    ]
    for i, h in enumerate(hs):
        expect = sum(i + r for r in range(size))
        np.testing.assert_allclose(
            hvd.synchronize(h), np.full((8,), float(expect)))

    # broadcast from the last process's first worker
    root_worker = hvd.local_size() * (size - 1)
    val = np.array([float(rank * 10 + 5)], np.float32)
    out = hvd.broadcast(val, root_rank=root_worker, name="b1")
    np.testing.assert_allclose(out, [float((size - 1) * 10 + 5)])

    # allgather with per-rank first dims
    mine = np.full((rank + 1, 2), float(rank), np.float32)
    out = hvd.allgather(mine, name="ag")
    assert out.shape == (total, 2), out.shape

    # reducescatter through the negotiated runtime: each rank gets its
    # reduced 1/P slice
    rs_in = np.arange(size * 3, dtype=np.float32) + rank
    out = hvd.reducescatter(rs_in, hvd.Sum, name="rs1")
    expect_full = sum(np.arange(size * 3, dtype=np.float32) + r
                      for r in range(size))
    np.testing.assert_allclose(out, expect_full[rank * 3:(rank + 1) * 3])

    # alltoall (even splits) through the native queue
    a2a_in = np.repeat(np.arange(size, dtype=np.float32), 2) + 100 * rank
    out = hvd.alltoall(a2a_in, name="a2a1")
    expect = np.repeat(np.full(size, float(rank)), 2) + 100 * np.repeat(
        np.arange(size, dtype=np.float32), 2)
    np.testing.assert_allclose(out, expect)

    # alltoall with uneven splits runs on the direct path behind a native
    # BARRIER flush, so it is safe even with async native ops in flight
    # (invariant #4): the barrier is dispatched after every co-negotiated
    # response, so no fused launch can interleave with the direct
    # collective.
    mine = np.arange(rank + size, dtype=np.float32)
    splits = [rank + 1] + [1] * (size - 1)
    h = hvd.allreduce_async(np.ones(4, np.float32), hvd.Sum, name="pend.t")
    out = hvd.alltoall(mine, splits=splits)
    assert out.shape[0] == sum(
        ([r + 1] + [1] * (size - 1))[rank] for r in range(size))
    np.testing.assert_allclose(
        hvd.synchronize(h), np.full(4, float(size)))

    # eager Adasum: distributed VHDD (2 procs = 1 ppermute round) vs oracle
    from horovod_tpu.ops import adasum as adasum_mod
    ada_in = (np.arange(6, dtype=np.float32) + 1) * (rank + 1)
    out = hvd.allreduce(ada_in, hvd.Adasum, name="ada.e")
    stacked = np.stack([(np.arange(6, dtype=np.float32) + 1) * (r + 1)
                        for r in range(size)])
    np.testing.assert_allclose(
        out, np.asarray(adasum_mod.adasum_reduce_stack(stacked)), rtol=1e-6)

    # De-flaked cache assertions: cycle skew (a rank popping its
    # submission a cycle before its peer sets the cache bit) forces
    # occasional slow-path fallbacks under host load, so fixed repeat
    # counts flake.  Instead run repeats in LOCKSTEP until every rank has
    # accumulated the wanted hit count — the exit condition is itself a
    # collective (Min over per-rank hit deltas, fresh name per iteration
    # so it never pollutes the hit counter), so all ranks execute the
    # same iteration count and the assertion holds at any scheduling
    # latency.
    def lockstep_until_hits(tag, want, body):
        base = rt.cache_hits()
        for i in range(200):
            body()
            mine = np.array([float(rt.cache_hits() - base)], np.float32)
            agreed = hvd.allreduce(mine, hvd.Min, name=f"{tag}.cond.{i}")
            if agreed[0] >= want:
                return
        raise AssertionError(
            f"{tag}: cache fast path never reached {want} hits on every "
            f"rank (local delta {rt.cache_hits() - base})")

    # response-cache steady state: repeats of the same name fast-path
    lockstep_until_hits(
        "cached", 3,
        lambda: hvd.allreduce(x, hvd.Sum, name="cached.t"))

    # allgather/alltoall response caching: first dims vary per rank, but
    # the cache key is the LOCAL request, so fixed-shape repeats ride the
    # bit-vector fast path too (reference response_cache.h:45-102).  The
    # first iteration negotiates (slow path); later ones must hit.
    ag_mine = np.full((rank + 1, 2), float(rank), np.float32)
    a2a_mine = np.repeat(np.arange(size, dtype=np.float32), 2)

    def gather_body():
        out = hvd.allgather(ag_mine, name="ag.cached")
        assert out.shape == (total, 2), out.shape
        hvd.alltoall(a2a_mine, name="a2a.cached")

    gather_body()  # first negotiation (slow path)
    lockstep_until_hits("agcache", 4, gather_body)

    # Invalidation: a changed first dim must MISS locally (the cache key
    # is this rank's own request), renegotiate globally, and produce the
    # correct new concatenation — then the refreshed entry caches again.
    grown = np.full((rank + 3, 2), float(rank), np.float32)

    def grown_body():
        out = hvd.allgather(grown, name="ag.cached")
        assert out.shape == (sum(r + 3 for r in range(size)), 2), out.shape

    grown_body()  # renegotiation with the new first dim
    lockstep_until_hits("agrow", 1, grown_body)

    # autotuner knob application: cycle time + cache capacity.  Resize on
    # rank 0 FIRST so the ranks' bit-vector lengths disagree for a few
    # cycles — the padded AllreduceBitsAndOr must self-heal via the
    # divergence slow path instead of erroring.
    rt.set_cycle_ms(0.5)
    if rank == 0:
        rt.set_cache_capacity(64)
    np.testing.assert_allclose(
        hvd.allreduce(x, hvd.Sum, name="skew.t"), np.full((4,), total))
    if rank != 0:
        rt.set_cache_capacity(64)
    for _ in range(3):
        np.testing.assert_allclose(
            hvd.allreduce(x, hvd.Sum, name="skew.t2"), np.full((4,), total))

    # coordinator-detected shape mismatch -> error on every rank
    if size > 1:
        try:
            hvd.allreduce(
                np.zeros((2 + rank,), np.float32), hvd.Sum, name="bad.shape")
            raise AssertionError("expected CollectiveError")
        except eager_runtime.CollectiveError as e:
            assert "Mismatched" in str(e), str(e)
        # runtime stays healthy after an error response
        np.testing.assert_allclose(
            hvd.allreduce(x, hvd.Sum, name="after.err"), np.full((4,), total))

    # Join: rank 0 leaves early; others keep reducing with rank 0
    # contributing zeros, then join too.  The return value is the rank
    # the coordinator saw join LAST — rank 0 went first, so it must be
    # one of the stragglers, never 0.
    if size > 1:
        if rank == 0:
            last = hvd.join()
        else:
            y = np.ones((3,), np.float32)
            np.testing.assert_allclose(
                hvd.allreduce(y, hvd.Sum, name="join.r"), y * (size - 1))
            np.testing.assert_allclose(
                hvd.allreduce(y, hvd.Average, name="join.r2"),
                y * (size - 1) / size)
            last = hvd.join()
        assert last != 0, f"rank 0 joined first yet join() returned {last}"
        np.testing.assert_allclose(
            hvd.allreduce(x, hvd.Sum, name="post.join"), np.full((4,), total))

        # Second round with rank 0 joining LAST: every rank must get 0 —
        # a value the pre-fix Max-of-ranks computation could never yield.
        # Event, not sleep: rank 0 hosts the coordinator, so it can wait
        # until the controller has SEEN every other rank's join before
        # submitting its own — deterministically last at any scheduling
        # latency (the joined_count gauge exists for exactly this).
        if rank == 0:
            deadline = time.time() + 120
            while rt.joined_count() < size - 1:
                assert time.time() < deadline, (
                    "stragglers' joins never reached the coordinator",
                    rt.joined_count())
                time.sleep(0.005)
        last = hvd.join()
        assert last == 0, f"rank 0 joined last yet join() returned {last}"
        np.testing.assert_allclose(
            hvd.allreduce(x, hvd.Sum, name="post.join2"),
            np.full((4,), total))

    # Sparse embedding-gradient reduction (the IndexedSlices-allgather
    # analogue): touched rows OVERLAP across ranks (row 10 everywhere),
    # and the (indices, values) allgather must equal the dense
    # allreduce while shipping only the touched rows.
    from horovod_tpu.ops import sparse as SP
    emb = np.zeros((32, 4), np.float32)
    for r_ in (rank, rank + 1, 10):
        emb[r_] = (r_ + 1.0) * (rank + 1.0)
    dense_ref = hvd.allreduce(emb, hvd.Average, name="spg.ref")
    sp_out, sp_stats = SP.sparse_allreduce(
        emb, hvd.Average, name="spg.t", return_stats=True)
    np.testing.assert_allclose(sp_out, dense_ref, rtol=1e-6)
    assert sp_stats["rows"] == 3 and sp_stats["total_rows"] == 32
    assert sp_stats["sparse_bytes"] < sp_stats["dense_bytes"] / 2

    # Empty contribution (ADVICE r5): the last rank touched ZERO rows
    # this step (an all-zero embedding grad is possible in real training)
    # — its (0,) / (0, D) submissions must ride the same allgatherv
    # round as its peers' nonzero contributions.
    emb2 = np.zeros((32, 4), np.float32)
    if rank != size - 1:
        emb2[2 * rank] = rank + 1.0
        emb2[11] = 3.0
    dense_ref2 = hvd.allreduce(emb2, hvd.Sum, name="spg.empty.ref")
    sp_out2, sp_stats2 = SP.sparse_allreduce(
        emb2, hvd.Sum, name="spg.empty", return_stats=True)
    np.testing.assert_allclose(sp_out2, dense_ref2, rtol=1e-6)
    expect_rows = 0 if rank == size - 1 else 2
    assert sp_stats2["rows"] == expect_rows, sp_stats2

    # All ranks empty: the degenerate gather (every contribution zero
    # rows) must return the zero gradient, not divide-by-zero or hang.
    zero = np.zeros((8, 2), np.float32)
    sp_out3 = SP.sparse_allreduce(zero, hvd.Average, name="spg.allempty")
    np.testing.assert_allclose(sp_out3, zero)

    hvd.barrier()
    hvd.shutdown()
    print(f"NATIVE-WORKER-OK rank={rank}")


def scenario_localsize():
    """The eager/in-graph worker-count seam (2 procs x 2 chips each):
    size() counts CHIPS, so eager reductions must weight each process's
    contribution by its local chip count — eager Sum/Average must equal
    the in-graph (worker-axis) collectives and sum/size()."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu import basics, spmd
    from horovod_tpu.ops import collectives as C

    assert hvd.num_processes() == size
    assert hvd.local_size() == 2, hvd.local_size()
    assert hvd.size() == 2 * size, hvd.size()

    x = np.full((3,), float(rank + 1), np.float32)  # process p holds p+1
    chip_sum = sum(2.0 * (p + 1) for p in range(size))

    out = hvd.allreduce(x, hvd.Sum, name="ls.sum")
    np.testing.assert_allclose(out, np.full((3,), chip_sum))
    avg = hvd.allreduce(x, hvd.Average, name="ls.avg")
    np.testing.assert_allclose(avg, np.full((3,), chip_sum / hvd.size()))

    # In-graph oracle over the full 4-chip mesh: every chip holds its
    # process's value; in-graph Average must equal the eager result.
    mesh = basics.mesh()
    ax = basics.axis_name()
    sharding = NamedSharding(mesh, P(ax))
    mine = [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
    shards = [jax.device_put(x[None], d) for d in mine]
    garr = jax.make_array_from_single_device_arrays(
        (hvd.size(), 3), sharding, shards)

    def fn(t):
        return C.allreduce(jnp.squeeze(t, 0), C.Average)[None]

    ingraph = spmd.run(fn, garr, in_specs=P(ax), out_specs=P(ax))
    local = np.asarray(ingraph.addressable_shards[0].data)[0]
    np.testing.assert_allclose(local, avg, rtol=1e-6)

    # Min/Max are insensitive to duplicate contributions.
    np.testing.assert_allclose(
        hvd.allreduce(x, hvd.Min, name="ls.min"), np.full((3,), 1.0))
    np.testing.assert_allclose(
        hvd.allreduce(x, hvd.Max, name="ls.max"), np.full((3,), float(size)))

    # process_sum: ONE contribution per process (the chip weighting
    # cancels) — the idiom for process-level payloads like row counts.
    np.testing.assert_allclose(
        hvd.process_sum(x, name="ls.psum"),
        np.full((3,), sum(p + 1 for p in range(size))))

    # reducescatter: chip-weighted Sum, Average divides by size().
    rs_in = np.tile(x, (size, 1))  # (size, 3): slice p goes to process p
    rs = hvd.reducescatter(rs_in, hvd.Average, name="ls.rs")
    np.testing.assert_allclose(
        rs, np.full((1, 3), chip_sum / hvd.size()).reshape(rs.shape))

    # Sparse (row-gathered) reduction must honor the same chip-weighted
    # contract with local_size() > 1: == the dense eager allreduce.
    from horovod_tpu.ops import sparse as SP
    sg = np.zeros((8, 3), np.float32)
    sg[rank] = rank + 1.0
    sg[5] = 10.0 * (rank + 1)  # overlapping row
    for op_ in (hvd.Sum, hvd.Average):
        np.testing.assert_allclose(
            SP.sparse_allreduce(sg, op_, name=f"ls.sp.{op_}"),
            np.asarray(hvd.allreduce(sg, op_, name=f"ls.spd.{op_}")),
            rtol=1e-6, err_msg=op_)

    hvd.barrier()
    hvd.shutdown()
    print(f"NATIVE-WORKER-OK rank={rank}")


if SCENARIO == "stall":
    scenario_stall()
elif SCENARIO == "localsize":
    scenario_localsize()
else:
    scenario_full()
