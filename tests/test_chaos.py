"""Chaos suite for the serving fault-tolerance layer.

THE invariant (docs/serving.md "Operations"): **no submitted request
ever hangs** — under injected device exceptions, non-finite logits,
hung ticks, and mid-stream cancellations, every
:class:`GenerationFuture` resolves with tokens or a typed error within
a bounded wall-clock, the engine recovers through supervised restarts,
and post-recovery greedy output is still token-identical to
per-request ``greedy_decode`` (the same oracle as
``tests/test_serving.py``).

Faults come from :class:`horovod_tpu.serving.FaultInjector` — seeded,
site-addressed, visit-counted — so every test here is deterministic:
same spec, same call sequence, same faults.  Engines are WARMED before
the watchdog is armed (first-tick XLA compilation would otherwise
read as a stall on CPU).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T

pytestmark = [pytest.mark.serving, pytest.mark.chaos]


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _engine(model, *, faults=None, **kw):
    params, cfg = model
    defaults = dict(n_slots=2, max_len=40, min_prefill_bucket=4,
                    restart_backoff=0.01, restart_backoff_max=0.05,
                    faults=faults)
    defaults.update(kw)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults))


def _run_until_done(engine, futs, max_ticks=300):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


def _warm(engine, prompt_lens=(3,)):
    """Compile every (prefill bucket, admission batch size) shape +
    the decode tick BEFORE arming the watchdog: XLA compilation takes
    seconds on CPU and must not read as a stall.  The sweep itself is
    the engine's own :meth:`warmup` — one definition, so warm coverage
    tracks the engine's compile-set shape."""
    engine.warmup(prompt_lens)


def _wait_for(pred, timeout=15.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


from conftest import http_post_json as _post  # noqa: E402
from conftest import parse_prometheus_text  # noqa: E402


class TestFaultInjector:
    def test_deterministic_and_site_addressed(self):
        def run():
            inj = serving.FaultInjector([
                serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=1, max_fires=2, p=0.5),
            ], seed=42)
            fired = []
            for _ in range(20):
                try:
                    inj.probe("decode_tick")
                except serving.InjectedFaultError:
                    fired.append(inj.fired[-1])
                inj.probe("prefill")  # other sites never fire this spec
            return fired, inj

        fired_a, inj_a = run()
        fired_b, _ = run()
        assert fired_a == fired_b            # same seed, same faults
        assert len(fired_a) == 2             # max_fires honored
        assert all(site == "decode_tick" for site, _, _ in fired_a)
        assert all(visit >= 1 for _, _, visit in fired_a)  # skip honored
        assert inj_a.exhausted

    def test_validation(self):
        with pytest.raises(ValueError, match="site"):
            serving.FaultInjector([serving.FaultSpec(site="nope")])
        with pytest.raises(ValueError, match="kind"):
            serving.FaultInjector(
                [serving.FaultSpec(site="prefill", kind="nope")])

    def test_hang_sleeps(self):
        inj = serving.FaultInjector([
            serving.FaultSpec(site="watchdog", kind="hang", delay=0.05)])
        t0 = time.monotonic()
        assert inj.probe("watchdog") == "hang"
        assert time.monotonic() - t0 >= 0.05
        assert inj.probe("watchdog") is None  # max_fires=1 default


class TestSupervisedRestart:
    """The PRE-RESUME contract (``resume=False``): a restart fails
    in-flight futures typed.  Kept as the explicit legacy mode — the
    default engine now RESUMES them instead (TestRestartResume)."""

    def test_decode_raise_fails_inflight_and_restarts(self, model):
        """A device exception mid-decode resolves every in-flight
        future with a typed EngineFailedError, restarts the engine
        (fresh SlotCache), and post-restart output is oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise", skip=1)])
        engine = _engine(model, faults=inj, resume=False)
        futs = [engine.submit([3, 4, 5], max_new_tokens=8),
                engine.submit([7, 8], max_new_tokens=8)]
        _run_until_done(engine, futs)
        for f in futs:
            with pytest.raises(serving.EngineFailedError):
                f.result(timeout=0)
        s = engine.stats()
        assert s["engine_failures"] == 1
        assert s["engine_restarts"] == 1
        assert "degraded" in s["state_transitions"]
        # recovery: the engine serves oracle-identical output
        fut = engine.submit([3, 4, 5], max_new_tokens=8)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [3, 4, 5], 8)
        assert engine.health == "healthy"

    def test_prefill_fault_fails_admitting_request(self, model):
        """A fault during admission (mid-prefill) must fail the request
        being admitted — it is in neither the queue nor a slot at that
        instant."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="prefill", kind="raise")])
        engine = _engine(model, faults=inj, resume=False)
        fut = engine.submit([5, 6, 7], max_new_tokens=6)
        _run_until_done(engine, [fut])
        with pytest.raises(serving.EngineFailedError):
            fut.result(timeout=0)
        fut = engine.submit([5, 6, 7], max_new_tokens=6)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [5, 6, 7], 6)
        assert engine.stats()["engine_restarts"] == 1

    def test_nonfinite_logits_typed_failure(self, model):
        """NaN logits out of the decode tick become a typed engine
        failure (never silently-greedy garbage tokens), then recovery."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="nonfinite")])
        engine = _engine(model, faults=inj, resume=False)
        fut = engine.submit([9, 10], max_new_tokens=5)
        _run_until_done(engine, [fut])
        with pytest.raises(serving.EngineFailedError, match="non-finite"):
            fut.result(timeout=0)
        fut = engine.submit([9, 10], max_new_tokens=5)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [9, 10], 5)

    def test_restart_budget_exhausted_goes_terminal(self, model):
        """Consecutive failures past max_restarts: the engine goes
        terminally failed, resolves the queue, and rejects new submits
        with a typed error — nothing ever hangs on a dead engine."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise",
                              max_fires=None)])
        engine = _engine(model, faults=inj, max_restarts=1, resume=False)
        f1 = engine.submit([1, 2], max_new_tokens=4)
        engine.step()  # admit + decode -> failure #1 -> restart
        assert engine.health == "degraded"
        with pytest.raises(serving.EngineFailedError):
            f1.result(timeout=0)
        f2 = engine.submit([3, 4], max_new_tokens=4)
        f3 = engine.submit([5, 6], max_new_tokens=4)
        engine.step()  # failure #2 > budget -> terminal
        assert engine.health == "failed"
        for f in (f2, f3):  # in-flight AND still-queued both resolved
            with pytest.raises(serving.EngineFailedError):
                f.result(timeout=0)
        with pytest.raises(serving.EngineFailedError):
            engine.submit([7], max_new_tokens=2)
        assert engine.step() is False  # dead engines don't tick
        s = engine.stats()
        assert s["state"] == "failed"
        assert s["engine_restarts"] == 1
        assert s["state_transitions"][-1] == "failed"
        # no phantom in-flight work on a dead engine
        assert s["slots_active"] == 0
        assert engine.slots.free_count == engine.engine_cfg.n_slots


class TestWatchdog:
    @pytest.mark.slow
    def test_stall_resolves_futures_then_recovers(self, model):
        """A hung tick: the watchdog fails in-flight + queued futures
        with EngineStalledError within the budget (the tick may never
        return); when it does return, the supervised restart brings the
        engine back to oracle-exact output."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, n_slots=2, resume=False,
                         tick_timeout=0.3, watchdog_interval=0.02)
        _warm(engine)
        # Scheduled RELATIVE to the post-warm visit count: the warm
        # phase must stay fault-free, and the overlapped pipeline's
        # tick count through warmup differs from the sync loop's.
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=1.2,
            skip=inj.visits("decode_tick") + 2))
        engine.start()
        try:
            t0 = time.monotonic()
            f_run = engine.submit([11, 12, 13], max_new_tokens=30)
            f_queued = engine.submit([14, 15], max_new_tokens=30)
            f_queued2 = engine.submit([16], max_new_tokens=30)
            # n_slots=2: f_run/f_queued admitted, f_queued2 waits.  The
            # 4th decode tick hangs 1.2s; the watchdog declares a stall
            # at ~0.3s and resolves ALL of them typed.
            for f in (f_run, f_queued, f_queued2):
                with pytest.raises(serving.EngineStalledError):
                    f.result(timeout=10.0)
            resolved_in = time.monotonic() - t0
            assert resolved_in < 1.2  # resolved BEFORE the hung tick ends
            assert "failed" in engine.state_transitions
            # the hung tick returns -> supervised restart -> healthy
            assert _wait_for(lambda: engine.health == "healthy")
            fut = engine.submit([11, 12, 13], max_new_tokens=6)
            assert fut.result(timeout=10.0) == _ref_greedy(
                params, cfg, [11, 12, 13], 6)
            s = engine.stats()
            assert s["engine_restarts"] >= 1
            assert "degraded" in s["state_transitions"]
        finally:
            engine.stop()

    def test_terminate_bounded_with_hung_tick_no_watchdog(self, model):
        """Watchdog disabled + hung tick: drain() must not inherit the
        hang (its lock acquire is timed), and terminate() still
        force-resolves every future in bounded time — teardown is
        bounded even when nothing else is."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, tick_timeout=0)
        _warm(engine)
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=1.5,
            skip=inj.visits("decode_tick") + 1))
        engine.start()
        try:
            fut = engine.submit([1, 2], max_new_tokens=10)
            assert _wait_for(lambda: engine.slots.active_count == 1,
                             timeout=5.0)
            time.sleep(0.1)  # now inside the 1.5s hang, _lock held
            t0 = time.monotonic()
            assert engine.drain(timeout=0.3) is False
            assert time.monotonic() - t0 < 1.0  # bounded, not hung
            engine.terminate("operator shutdown")
            assert time.monotonic() - t0 < 2.0
            with pytest.raises(serving.EngineFailedError):
                fut.result(timeout=1.0)
            assert engine.health == "failed"
            # the late-returning tick may only land terminal, never a
            # restart that reopens the engine
            time.sleep(1.6)
            assert engine.health == "failed"
            with pytest.raises(serving.EngineFailedError):
                engine.submit([3], max_new_tokens=2)
        finally:
            engine.stop()

    def test_draining_sticky_across_stall_recovery(self, model):
        """A stall overwrites DRAINING with FAILED; the recovery
        restart must restore DRAINING — never reopen a draining engine
        as DEGRADED behind a still-open listener."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, tick_timeout=0.2,
                         resume=False, watchdog_interval=0.02)
        _warm(engine)
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=0.8,
            skip=inj.visits("decode_tick") + 1))
        engine.start()
        try:
            fut = engine.submit([1, 2], max_new_tokens=20)
            engine.begin_drain()
            with pytest.raises(serving.EngineStalledError):
                fut.result(timeout=10.0)
            assert _wait_for(
                lambda: engine.metrics.engine_restarts.value >= 1)
            assert engine.health == "draining"
            with pytest.raises(serving.DrainingError):
                engine.submit([3], max_new_tokens=2)
        finally:
            engine.stop()

    def test_hang_before_admission_fails_queued(self, model):
        """A stall while requests are still QUEUED (hang at the
        watchdog probe site, before admission) resolves them too — the
        queue is never left behind a hung engine."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="watchdog", kind="hang", delay=0.9,
                              skip=0)])
        engine = _engine(model, faults=inj, tick_timeout=0.2,
                         resume=False, watchdog_interval=0.02)
        # Submit BEFORE start: the very first step hangs ahead of
        # admission, so both requests are queued when the stall lands.
        f1 = engine.submit([1, 2], max_new_tokens=4)
        f2 = engine.submit([3, 4], max_new_tokens=4)
        engine.start()
        try:
            for f in (f1, f2):
                with pytest.raises(serving.EngineStalledError):
                    f.result(timeout=10.0)
            assert _wait_for(lambda: engine.health == "healthy")
        finally:
            engine.stop()


class TestDecodeFetchFaults:
    """Faults at the overlapped pipeline's deferred-fetch boundary —
    the one host sync per steady-state tick, where an async device
    failure from the PREVIOUS tick actually surfaces.  The invariant
    is unchanged: every submitted request resolves with tokens or a
    typed error, and the engine recovers to oracle-exact output with
    zero decode recompiles."""

    def test_fetch_raise_fails_inflight_and_restarts(self, model):
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_fetch", kind="raise",
                              skip=2)])
        engine = _engine(model, faults=inj, resume=False)
        assert engine.engine_cfg.overlap  # the deferred-fetch path
        futs = [engine.submit([3, 4, 5], max_new_tokens=8),
                engine.submit([7, 8], max_new_tokens=8)]
        _run_until_done(engine, futs)
        for f in futs:
            with pytest.raises(serving.EngineFailedError):
                f.result(timeout=0)
        assert inj.fired[0][0] == "decode_fetch"
        s = engine.stats()
        assert s["engine_failures"] == 1 and s["engine_restarts"] == 1
        # recovery: fresh pipeline state, oracle-exact output
        fut = engine.submit([3, 4, 5], max_new_tokens=8)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [3, 4, 5], 8)
        assert engine.decode_compilations == 1

    def test_fetch_hang_trips_watchdog(self, model):
        """A fetch that never returns (device wedged after accepting
        the dispatch): the watchdog resolves in-flight AND queued
        futures inside its budget, and the engine recovers when the
        fetch finally lands."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, n_slots=1, resume=False,
                         tick_timeout=0.25, watchdog_interval=0.02)
        _warm(engine)
        inj.add(serving.FaultSpec(
            site="decode_fetch", kind="hang", delay=1.0,
            skip=inj.visits("decode_fetch") + 1))
        engine.start()
        try:
            t0 = time.monotonic()
            f_run = engine.submit([11, 12], max_new_tokens=30)
            f_queued = engine.submit([13], max_new_tokens=30)
            for f in (f_run, f_queued):
                with pytest.raises(serving.EngineStalledError):
                    f.result(timeout=10.0)
            assert time.monotonic() - t0 < 1.0  # before the hang ends
            assert _wait_for(lambda: engine.health == "healthy")
            fut = engine.submit([11, 12], max_new_tokens=5)
            assert fut.result(timeout=10.0) == _ref_greedy(
                params, cfg, [11, 12], 5)
        finally:
            engine.stop()

    @pytest.mark.slow
    def test_invariant_under_mixed_fetch_faults(self, model):
        """Chaos invariant at the new site with overlap on: raise and
        hang at decode_fetch under load — 100% of requests resolve
        with tokens or a typed error, and the engine ends healthy and
        oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector(seed=3)
        engine = _engine(model, faults=inj, n_slots=2, max_restarts=10,
                         tick_timeout=0.3, watchdog_interval=0.02,
                         max_queue_depth=32)
        # Warm the RESUME buckets too (a resumed prompt is prompt +
        # emitted, i.e. up to 4 + 10 tokens): an unwarmed re-admission
        # would pay XLA compilation inside the 0.3s watchdog budget
        # and read as a second stall.
        _warm(engine, prompt_lens=(3, 7, 15))
        base = inj.visits("decode_fetch")
        inj.add(
            serving.FaultSpec(site="decode_fetch", kind="raise",
                              skip=base + 3),
            serving.FaultSpec(site="decode_fetch", kind="hang",
                              delay=0.8, skip=base + 9),
        )
        engine.start()
        rng = np.random.default_rng(7)
        try:
            futs = []
            for i in range(10):
                prompt = rng.integers(0, cfg.vocab_size,
                                      2 + i % 3).tolist()
                try:
                    futs.append(engine.submit(prompt, max_new_tokens=10))
                except serving.ServingError:
                    pass
            for f in futs:
                try:
                    f.result(timeout=30.0)
                except serving.ServingError:
                    pass  # typed = resolved; TimeoutError would fail
            assert all(f.done() for f in futs)
            burn = time.monotonic() + 20.0
            while not inj.exhausted:
                assert time.monotonic() < burn, "faults never exhausted"
                if engine.health in ("healthy", "degraded"):
                    try:
                        f = engine.submit([1, 2], max_new_tokens=6)
                        try:
                            f.result(timeout=10.0)
                        except serving.ServingError:
                            pass
                    except serving.ServingError:
                        pass
                else:
                    time.sleep(0.05)
            assert _wait_for(lambda: engine.health == "healthy")
            fut = engine.submit([30, 31], max_new_tokens=8)
            assert fut.result(timeout=15.0) == _ref_greedy(
                params, cfg, [30, 31], 8)
            assert engine.stats()["decode_compilations"] == 1
        finally:
            engine.stop()


class TestRestartResume:
    """ACCEPTANCE (ISSUE 9): in-flight requests are DURABLE.  With
    ``resume`` (the default), an engine crash or stall at ANY decode
    depth costs one tick plus one re-prefill, never the request: the
    journaled state (prompt, params, tokens emitted so far) is
    re-admitted after the supervised restart with the ORIGINAL future
    still live, and the concatenated output is byte-identical to the
    no-fault greedy oracle — no ``EngineFailedError`` for resumable
    requests."""

    def _crash_at_depth(self, model, depth, *, site="decode_tick",
                        kind="raise", max_new=8, **kw):
        """Drive a request to ``depth`` emitted tokens, then inject a
        fault on the next visit of ``site``; run to completion."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, **kw)
        fut = engine.submit([3, 4, 5], max_new_tokens=max_new)
        other = engine.submit([7, 8], max_new_tokens=max_new)
        for _ in range(300):
            if len(fut.tokens_so_far()) >= depth or fut.done():
                break
            engine.step()
        assert not fut.done()
        inj.add(serving.FaultSpec(site=site, kind=kind,
                                  skip=inj.visits(site)))
        _run_until_done(engine, [fut, other])
        return engine, fut, other

    # depth 1 rides tier-1; the deeper sweep is budget-marked slow
    # (tests/DURATIONS.md) and runs with the full chaos suite.
    @pytest.mark.parametrize("depth", [
        1,
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
        pytest.param(7, marks=pytest.mark.slow),
    ])
    def test_crash_at_every_decode_depth_output_oracle_exact(
            self, model, depth):
        """depth 1 = the first decode tick after admission, 7 =
        the tick producing the LAST token (max_new_tokens=8; token 1
        comes from prefill) — the full sweep the issue demands."""
        params, cfg = model
        engine, fut, other = self._crash_at_depth(model, depth)
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [3, 4, 5], 8)
        assert other.result(timeout=0) == _ref_greedy(params, cfg,
                                                      [7, 8], 8)
        s = engine.stats()
        assert s["engine_restarts"] == 1
        assert s["requests_resumed"] >= 1
        # wasted work is bounded: ONE re-prefill of prompt + emitted
        # per resumed request (plus the crashed tick itself)
        assert s["resume_wasted_tokens"] <= (3 + depth) + (2 + depth + 1)
        assert s["journal_inflight"] == 0  # all entries retired
        assert engine.health == "healthy"

    def test_crash_during_admission_resumes_taken_requests(self, model):
        """Depth 0: a prefill fault hits requests that are TAKEN but
        not yet landed — they resume with zero emitted tokens (a plain
        re-admission) instead of failing typed."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="prefill", kind="raise")])
        engine = _engine(model, faults=inj)
        fut = engine.submit([5, 6, 7], max_new_tokens=6)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [5, 6, 7], 6)
        s = engine.stats()
        assert s["requests_resumed"] == 1
        assert s["engine_restarts"] == 1

    @pytest.mark.slow
    def test_nonfinite_crash_resumes(self, model):
        # Slow (PR 17 budget pass): ~4 s; test_nonfinite_logits_typed_
        # failure keeps the nonfinite detection tier-1 and the resume
        # path is exercised by the rest of TestRestartResume.
        """Non-finite logits poison the tick BEFORE emission — nothing
        from the bad tick is journaled, and the resume replays only
        oracle-emitted tokens."""
        params, cfg = model
        engine, fut, other = self._crash_at_depth(model, 3,
                                                  kind="nonfinite")
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [3, 4, 5], 8)

    def test_fetch_crash_resumes(self, model):
        """A fault at the overlapped pipeline's deferred-fetch boundary
        loses the in-flight tick (the one tick of allowed waste) but
        never an emitted token."""
        params, cfg = model
        engine, fut, other = self._crash_at_depth(model, 2,
                                                  site="decode_fetch")
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [3, 4, 5], 8)
        assert engine.stats()["decode_compilations"] == 1

    def test_repeated_crashes_still_oracle_exact(self, model):
        """Two crashes against the SAME request: emitted tokens
        accumulate in the journal, each resume re-prefills the full
        frontier, output stays exact."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, max_restarts=5)
        fut = engine.submit([9, 10], max_new_tokens=10)
        for depth in (2, 5):
            for _ in range(300):
                if len(fut.tokens_so_far()) >= depth or fut.done():
                    break
                engine.step()
            inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                      skip=inj.visits("decode_tick")))
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [9, 10], 10)
        assert engine.stats()["requests_resumed"] == 2

    def test_fault_in_resume_machinery_degrades_to_typed(self, model):
        """The new ``restart_resume`` fault site: when the resume
        machinery itself fails, the engine falls back to the legacy
        fail-typed restart — in-flight futures resolve with
        EngineFailedError (never a replay from untrusted state), and
        the engine still recovers to oracle-exact output."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise", skip=2),
            serving.FaultSpec(site="restart_resume", kind="raise")])
        engine = _engine(model, faults=inj)
        fut = engine.submit([3, 4, 5], max_new_tokens=8)
        _run_until_done(engine, [fut])
        with pytest.raises(serving.EngineFailedError):
            fut.result(timeout=0)
        assert ("restart_resume", "raise", 0) in inj.fired
        s = engine.stats()
        assert s["requests_resumed"] == 0
        assert s["journal_inflight"] == 0  # still purged, no ghosts
        f2 = engine.submit([3, 4, 5], max_new_tokens=8)
        _run_until_done(engine, [f2])
        assert f2.result(timeout=0) == _ref_greedy(params, cfg,
                                                   [3, 4, 5], 8)

    @pytest.mark.slow
    def test_stall_within_grace_resumes(self, model):
        """A hung tick that RETURNS inside stall_grace: the watchdog
        holds the in-flight futures (no EngineStalledError), and the
        supervised restart resumes them to oracle-exact output."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, n_slots=2,
                         tick_timeout=0.3, watchdog_interval=0.02,
                         stall_grace=15.0)
        _warm(engine, prompt_lens=(3, 5, 9, 17))  # resume buckets too
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=1.0,
            skip=inj.visits("decode_tick") + 2))
        engine.start()
        try:
            fut = engine.submit([11, 12, 13], max_new_tokens=8)
            assert fut.result(timeout=30.0) == _ref_greedy(
                params, cfg, [11, 12, 13], 8)
            s = engine.stats()
            assert s["requests_resumed"] >= 1
            assert "failed" in s["state_transitions"]  # the stall
            assert _wait_for(lambda: engine.health == "healthy")
        finally:
            engine.stop()

    def test_stall_past_grace_hard_fails_bounded(self, model):
        """The bounded-resolution backstop: a stall that outlives
        budget + stall_grace resolves every future typed from the
        watchdog thread, purges the journal (a zombie tick returning
        later finds NOTHING to resume), and the engine still
        recovers."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, n_slots=2,
                         tick_timeout=0.2, watchdog_interval=0.02,
                         stall_grace=0.2)
        _warm(engine)
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=1.5,
            skip=inj.visits("decode_tick") + 2))
        engine.start()
        try:
            t0 = time.monotonic()
            f_run = engine.submit([11, 12, 13], max_new_tokens=30)
            f_q = engine.submit([14, 15], max_new_tokens=30)
            f_q2 = engine.submit([16], max_new_tokens=30)
            for f in (f_run, f_q, f_q2):
                with pytest.raises(serving.EngineStalledError):
                    f.result(timeout=10.0)
            assert time.monotonic() - t0 < 1.5  # before the hang ends
            assert engine.stats()["journal_inflight"] == 0
            # zombie tick returns -> restart finds nothing to resume
            assert _wait_for(lambda: engine.health == "healthy")
            assert engine.stats()["requests_resumed"] == 0
            fut = engine.submit([11, 12], max_new_tokens=5)
            assert fut.result(timeout=15.0) == _ref_greedy(
                params, cfg, [11, 12], 5)
        finally:
            engine.stop()

    def test_deadline_survives_resume(self, model):
        """SATELLITE: the deadline is the REMAINING budget, never a
        fresh one — a deadline that lapses during the restart backoff
        resolves when the resumed request reaches the queue head.
        Since PR 14 an ADMITTED-ONCE request honors the
        deadline-after-admission contract there: it FINISHES with the
        partial tokens a previous life emitted (reason "deadline"),
        never a 504 that discards paid-for output."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, restart_backoff=0.4,
                         restart_backoff_max=0.4)
        _warm(engine)
        fut = engine.submit([3, 4, 5], max_new_tokens=20,
                            deadline=time.monotonic() + 0.3)
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2 or fut.done():
                break
            engine.step()
        assert not fut.done()
        emitted = len(fut.tokens_so_far())
        assert emitted >= 2
        inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=inj.visits("decode_tick")))
        _run_until_done(engine, [fut])
        assert fut.finish_reason == "deadline"
        out = fut.result(timeout=0)  # partial result, no exception
        assert len(out) >= emitted and len(out) < 20
        assert out == _ref_greedy(model[0], model[1],
                                  [3, 4, 5], 20)[:len(out)]

    def test_cancelled_request_not_resumed(self, model):
        """A cancellation pending at crash time resolves as
        "cancelled" (tokens so far) — never re-admitted."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj)
        fut = engine.submit([21, 22], max_new_tokens=20)
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2:
                break
            engine.step()
        fut.cancel()
        inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=inj.visits("decode_tick")))
        _run_until_done(engine, [fut])
        assert fut.finish_reason == "cancelled"
        assert engine.stats()["requests_resumed"] == 0
        assert engine.stats()["journal_inflight"] == 0

    def test_retired_request_never_ghost_readmitted(self, model):
        """SATELLITE (no ghosts): a request that retired BEFORE the
        crash stays retired — its journal entry died with its
        resolution, so the restart re-admits nothing."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj)
        done = engine.submit([5, 6], max_new_tokens=3)
        _run_until_done(engine, [done])
        assert done.result(timeout=0) == _ref_greedy(params, cfg,
                                                     [5, 6], 3)
        admitted_before = engine.metrics.admitted.value
        inj.add(serving.FaultSpec(site="watchdog", kind="raise",
                                  skip=inj.visits("watchdog")))
        fresh = engine.submit([7, 8], max_new_tokens=3)  # drives ticks
        _run_until_done(engine, [fresh])
        s = engine.stats()
        assert s["requests_resumed"] <= 1  # only `fresh` may resume
        # `done` was never re-admitted
        assert engine.metrics.admitted.value <= admitted_before + 1
        assert done.result(timeout=0) == _ref_greedy(params, cfg,
                                                     [5, 6], 3)

    @pytest.mark.slow
    def test_resume_invariant_under_chaos_load(self, model):
        """The PR 3 chaos invariant, upgraded: faults at every site
        under load, and every request whose future was never
        hard-failed completes with tokens ORACLE-EXACT — durability
        composes with the bounded-resolution guarantee."""
        params, cfg = model
        inj = serving.FaultInjector(seed=1)
        engine = _engine(model, faults=inj, n_slots=4, max_restarts=20,
                         max_queue_depth=64)
        _warm(engine, prompt_lens=(3, 7, 15, 29))
        pre, dec = inj.visits("prefill"), inj.visits("decode_tick")
        fetch = inj.visits("decode_fetch")
        inj.add(
            serving.FaultSpec(site="prefill", kind="raise", skip=pre + 1),
            serving.FaultSpec(site="decode_tick", kind="raise",
                              skip=dec + 4),
            serving.FaultSpec(site="decode_fetch", kind="raise",
                              skip=fetch + 9),
            serving.FaultSpec(site="decode_tick", kind="nonfinite",
                              skip=dec + 14),
        )
        rng = np.random.default_rng(5)
        futs, prompts = [], []
        for i in range(12):
            prompt = rng.integers(0, cfg.vocab_size, 2 + i % 7).tolist()
            prompts.append(prompt)
            futs.append(engine.submit(prompt, max_new_tokens=10))
        for _ in range(3000):
            if all(f.done() for f in futs):
                break
            engine.step()
        for prompt, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg,
                                                      prompt, 10)
        s = engine.stats()
        assert s["engine_failures"] >= 4
        assert s["requests_resumed"] >= 4
        assert s["decode_compilations"] == 1  # restarts swap the cache,
        assert s["journal_inflight"] == 0     # never the program
        assert engine.health == "healthy"


class TestJournalDurability:
    """The file-backed journal (EngineConfig.journal_path): what a
    SIGKILL'd replica leaves behind, and what the router reads
    post-mortem (tests/test_router.py proves the cross-process arc)."""

    def test_live_entries_match_futures_and_survive_reread(
            self, model, tmp_path):
        params, cfg = model
        jp = str(tmp_path / "req.journal.jsonl")
        engine = _engine(model, journal_path=jp)
        fut = engine.submit([3, 4, 5], max_new_tokens=8,
                            trace_id="tr-live",
                            deadline=time.monotonic() + 30.0)
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 3:
                break
            engine.step()
        live = serving.RequestJournal.read_live(jp)
        desc = live["tr-live"]
        assert desc["emitted_tokens"] == fut.tokens_so_far()
        assert desc["prompt"] == [3, 4, 5]
        assert desc["max_new_tokens"] == 8
        assert 0 < desc["deadline_remaining_ms"] <= 30000
        _run_until_done(engine, [fut])
        assert serving.RequestJournal.read_live(jp) == {}

    def test_terminate_purges_journal_no_ghosts(self, model, tmp_path):
        """SATELLITE: terminate() of a resumable request purges its
        journal entry — the post-mortem reader sees nothing to
        resume."""
        jp = str(tmp_path / "req.journal.jsonl")
        engine = _engine(model, journal_path=jp)
        fut = engine.submit([3, 4, 5], max_new_tokens=20,
                            trace_id="tr-term")
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2:
                break
            engine.step()
        assert len(serving.RequestJournal.read_live(jp)) == 1
        engine.terminate("operator shutdown")
        with pytest.raises(serving.EngineFailedError):
            fut.result(timeout=0)
        assert serving.RequestJournal.read_live(jp) == {}
        assert len(engine.journal) == 0

    def test_journal_links_resume_into_the_originating_span(
            self, model, tmp_path):
        """SATELLITE (ISSUE 12): journal entries carry the originating
        SPAN id, so a post-mortem lookup after a SIGKILL hands the
        router the dead attempt's span — the resumed attempt links
        into the SAME trace tree instead of starting an orphan.  The
        id must survive the full round trip: begin record, compaction
        rewrite, and the read_live descriptor."""
        jp = str(tmp_path / "req.journal.jsonl")
        engine = _engine(model, journal_path=jp)
        fut = engine.submit([3, 4, 5], max_new_tokens=12,
                            trace_id="tr-span")
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2:
                break
            engine.step()
        span_id = fut.trace.span_id
        assert span_id  # minted at submit, with or without a recorder
        live = serving.RequestJournal.read_live(jp)
        assert live["tr-span"]["span_id"] == span_id
        # compaction preserves it (the rewrite path re-serializes)
        engine.journal._dead_lines = engine.journal.COMPACT_AFTER
        engine.journal.end(-1)  # no-op purge, but triggers nothing
        with engine.journal._lock:
            engine.journal._compact_locked()
        live = serving.RequestJournal.read_live(jp)
        assert live["tr-span"]["span_id"] == span_id
        _run_until_done(engine, [fut])

    def test_arrival_and_stream_survive_roundtrip_and_compaction(
            self, model, tmp_path):
        """SATELLITE (ISSUE 17): begin lines carry the request's
        ARRIVAL (monotonic offset from journal open + wall clock) and
        streaming flag, so a journaled trace replays at original
        spacing (horovod_tpu/tuning/replay.py).  Both must survive
        the full round trip — begin record, compaction rewrite,
        read_live — and stay OPTIONAL for old journals (a begin line
        without them still parses)."""
        jp = str(tmp_path / "req.journal.jsonl")
        engine = _engine(model, journal_path=jp)
        fut = engine.submit([3, 4, 5], max_new_tokens=12,
                            trace_id="tr-arr",
                            on_token=lambda t, p: None)
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2:
                break
            engine.step()
        raw = [json.loads(ln) for ln in open(jp)]
        begin = [ev for ev in raw if ev["e"] == "b"][0]
        mono, wall = begin["arr"]
        assert 0.0 <= mono < 60.0          # offset from journal open
        assert abs(wall - time.time()) < 60.0
        assert begin["stream"] == 1        # on_token was set
        # compaction re-serializes live entries: both fields survive
        with engine.journal._lock:
            engine.journal._compact_locked()
        raw = [json.loads(ln) for ln in open(jp)]
        begin2 = [ev for ev in raw if ev["e"] == "b"][0]
        assert begin2["arr"] == [mono, wall]
        assert begin2["stream"] == 1
        # ... and through the replay-trace reader
        from horovod_tpu.tuning.replay import read_trace

        req = read_trace(jp)[0]
        assert (req.arrival, req.stream) == (mono, True)
        # byte-compat: a pre-arrival begin line (no arr/stream keys)
        # still parses, replaying at zero offset, non-streamed
        with open(jp, "w") as f:
            f.write('{"e":"b","id":9,"prompt":[1,2],"max_new":4,'
                    '"trace":"tr-old"}\n')
        old = read_trace(jp)[0]
        assert (old.arrival, old.stream) == (0.0, False)
        assert serving.RequestJournal.read_live(jp)  # old reader path
        _run_until_done(engine, [fut])

    def test_torn_final_line_tolerated(self, model, tmp_path):
        """A SIGKILL can land mid-write: every complete line before
        the torn one still parses."""
        jp = str(tmp_path / "req.journal.jsonl")
        engine = _engine(model, journal_path=jp)
        fut = engine.submit([3, 4], max_new_tokens=8, trace_id="tr-torn")
        for _ in range(300):
            if len(fut.tokens_so_far()) >= 2:
                break
            engine.step()
        with open(jp, "a") as f:
            f.write('{"e":"t","id":')  # torn mid-write
        live = serving.RequestJournal.read_live(jp)
        assert live["tr-torn"]["emitted_tokens"] == fut.tokens_so_far()

    def test_http_engine_failed_carries_resume_descriptor(self, model):
        """SATELLITE (contract upward): a terminal engine failure's
        503 carries the resume descriptor — emitted tokens and the
        REMAINING deadline budget — so a front tier can continue the
        request elsewhere."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, max_restarts=0)
        _warm(engine)
        inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=inj.visits("decode_tick") + 2))
        with serving.ServingServer(engine, port=0,
                                   request_timeout=30.0) as srv:
            host, port = srv.address
            code, out = _post(
                f"http://{host}:{port}/generate",
                {"tokens": [1, 2], "max_new_tokens": 30,
                 "timeout_ms": 25000})
            assert (code, out["type"]) == (503, "engine_failed")
            res = out["resume"]
            assert len(res["emitted_tokens"]) >= 1
            assert 0 < res["deadline_remaining_ms"] <= 25000


class TestCancellation:
    def test_cancel_midstream_reclaims_slot(self, model):
        params, cfg = model
        engine = _engine(model)
        fut = engine.submit([21, 22], max_new_tokens=30)
        engine.step()
        engine.step()
        n_before = len(fut.tokens_so_far())
        assert 0 < n_before < 30
        assert fut.cancel() is True
        engine.step()  # reclamation tick
        assert fut.done() and fut.finish_reason == "cancelled"
        assert fut.cancelled
        toks = fut.result(timeout=0)  # resolves with partial tokens
        assert len(toks) == n_before < 30
        assert engine.slots.active_count == 0  # slot reclaimed
        assert engine.stats()["requests_cancelled"] == 1
        # the freed slot serves the next request, oracle-exact
        fut = engine.submit([21, 22], max_new_tokens=5)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [21, 22], 5)

    def test_cancel_queued_never_admitted(self, model):
        engine = _engine(model, n_slots=1)
        f_run = engine.submit([1, 2], max_new_tokens=20)
        f_queued = engine.submit([3, 4], max_new_tokens=20)
        engine.step()  # f_run takes the only slot
        assert f_queued.cancel() is True
        engine.step()  # queue purge: cancelled head never takes a slot
        assert f_queued.done() and f_queued.finish_reason == "cancelled"
        assert f_queued.result(timeout=0) == []
        assert engine.stats()["requests_admitted"] == 1
        f_run.cancel()
        engine.step()
        assert f_run.done()

    def test_cancel_after_done_is_noop(self, model):
        engine = _engine(model)
        fut = engine.submit([5, 6], max_new_tokens=2)
        _run_until_done(engine, [fut])
        assert fut.cancel() is False
        assert fut.finish_reason == "length"


class TestChaosInvariant:
    @pytest.mark.slow
    def test_no_submitted_request_ever_hangs(self, model):
        """ACCEPTANCE: faults at every site — raise, non-finite, and a
        watchdog-tripping hang — against a loaded background engine.
        100% of submitted requests resolve with tokens or a typed
        error within a bounded wall-clock (zero hung futures), the
        engine recovers, serves oracle-identical greedy output, and
        the restarts + health transitions are visible in stats."""
        params, cfg = model
        inj = serving.FaultInjector(seed=0)
        engine = _engine(model, faults=inj, n_slots=4, max_restarts=10,
                         tick_timeout=0.3, watchdog_interval=0.02,
                         max_queue_depth=64)
        # Every prompt bucket AND every resume bucket (prompt + up to
        # 16 emitted tokens -> bucket 32), every k: a resumed
        # re-admission must never pay XLA compilation inside the 0.3s
        # watchdog budget.
        _warm(engine, prompt_lens=(3, 7, 15, 29))
        # Faults scheduled RELATIVE to the post-warm visit counts so
        # every spec fires under the load phase, not during warmup.
        pre, dec = inj.visits("prefill"), inj.visits("decode_tick")
        inj.add(
            serving.FaultSpec(site="prefill", kind="raise", skip=pre + 1),
            serving.FaultSpec(site="decode_tick", kind="raise",
                              skip=dec + 4),
            serving.FaultSpec(site="decode_tick", kind="nonfinite",
                              skip=dec + 9),
            serving.FaultSpec(site="decode_tick", kind="hang",
                              delay=0.8, skip=dec + 14),
        )
        engine.start()
        rng = np.random.default_rng(5)
        t0 = time.monotonic()
        try:
            futs = []
            for i in range(16):
                prompt = rng.integers(0, cfg.vocab_size,
                                      2 + i % 7).tolist()
                try:
                    futs.append(engine.submit(prompt, max_new_tokens=16))
                except serving.ServingError:
                    pass  # typed submit-time rejection = resolved too
            # THE invariant: every future resolves inside the bound —
            # tokens or a typed ServingError, never a hang.
            outcomes = {"ok": 0, "typed_error": 0}
            for f in futs:
                try:
                    f.result(timeout=30.0)
                    outcomes["ok"] += 1
                except serving.ServingError:
                    outcomes["typed_error"] += 1
            # (TimeoutError would propagate and fail the test: a hang.)
            assert outcomes["ok"] + outcomes["typed_error"] == len(futs)
            assert time.monotonic() - t0 < 60.0

            # Burn off any fault that hasn't fired yet (e.g. the hang,
            # if earlier failures emptied the pool first) so recovery
            # is tested on a genuinely fault-free engine.
            burn_deadline = time.monotonic() + 30.0
            while not inj.exhausted:
                assert time.monotonic() < burn_deadline, \
                    "faults never exhausted"
                if engine.health in ("healthy", "degraded"):
                    try:
                        f = engine.submit([1, 2, 3], max_new_tokens=8)
                        try:
                            f.result(timeout=10.0)
                        except serving.ServingError:
                            pass
                    except serving.ServingError:
                        pass
                else:
                    time.sleep(0.05)

            assert _wait_for(lambda: engine.health == "healthy")
            # Recovery correctness: oracle-identical greedy output.
            prompt = [30, 31, 32]
            fut = engine.submit(prompt, max_new_tokens=10)
            assert fut.result(timeout=15.0) == _ref_greedy(
                params, cfg, prompt, 10)
            s = engine.stats()
            assert s["engine_failures"] >= 4   # all four specs fired
            assert s["engine_restarts"] >= 3
            assert s["state"] == "healthy"
            assert "degraded" in s["state_transitions"]
            assert "failed" in s["state_transitions"]  # the stall
            # the decode executable NEVER recompiled — restarts swap
            # the cache, not the program
            assert s["decode_compilations"] == 1
        finally:
            engine.stop()


class TestChunkedPrefillChaos:
    """The ``prefill_chunk`` FaultInjector site (PR 14): chunk-
    boundary crashes are in the chaos invariant — a fault at ANY
    chunk of a chunked prompt ingestion suspends the request through
    the ordinary resume path and the re-ingested output is
    token-identical to the no-fault oracle."""

    # chunks 1/3 are slow (PR 17 budget pass): chunk 0 keeps the
    # crash-at-a-chunk-boundary resume path tier-1; the later
    # boundaries re-run the same site with landed pages to discard.
    @pytest.mark.parametrize(
        "chunk_idx",
        [0,
         pytest.param(1, marks=pytest.mark.slow),
         pytest.param(3, marks=pytest.mark.slow)])
    def test_crash_at_each_chunk_boundary_oracle_exact(self, model,
                                                       chunk_idx):
        params, cfg = model
        inj = serving.FaultInjector([serving.FaultSpec(
            site="prefill_chunk", kind="raise", skip=chunk_idx)])
        engine = _engine(model, faults=inj, prefill_chunk_tokens=8,
                         tick_timeout=0)
        rng = np.random.default_rng(31 + chunk_idx)
        long_p = rng.integers(1, cfg.vocab_size, 30).tolist()
        short_p = [4, 2]
        vic = engine.submit(long_p, max_new_tokens=4)
        sh = engine.submit(short_p, max_new_tokens=3)
        _run_until_done(engine, [vic, sh], max_ticks=600)
        assert inj.fired == [("prefill_chunk", "raise", chunk_idx)]
        assert vic.result(timeout=0) == _ref_greedy(
            params, cfg, long_p, 4)
        assert sh.result(timeout=0) == _ref_greedy(
            params, cfg, short_p, 3)
        s = engine.stats()
        assert s["engine_restarts"] == 1
        assert s["decode_compilations"] <= 1
        assert s["slots_ingesting"] == 0 and s["queue_depth"] == 0

    @pytest.mark.slow
    def test_chunk_hang_trips_watchdog_and_resumes(self, model):
        # Slow (PR 17 budget pass): hang + watchdog grace is ~8 s;
        # test_fetch_hang_trips_watchdog keeps the hang-site watchdog
        # path tier-1 and chunk crashes are covered just above.
        """A HANG inside a chunk dispatch trips the watchdog like any
        stalled tick; the tick returns inside the resume grace, the
        supervised restart re-ingests, and output stays
        oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, prefill_chunk_tokens=8,
                         tick_timeout=0.3, watchdog_interval=0.02,
                         stall_grace=10.0)
        _warm(engine, prompt_lens=(3,))
        # warm the chunk shapes too, fault-free, then schedule the
        # hang relative to the post-warm visit count
        rng = np.random.default_rng(37)
        warm_p = rng.integers(1, cfg.vocab_size, 30).tolist()
        f0 = engine.submit(warm_p, max_new_tokens=2)
        _run_until_done(engine, [f0], max_ticks=600)
        inj.add(serving.FaultSpec(site="prefill_chunk", kind="hang",
                                  delay=0.8,
                                  skip=inj.visits("prefill_chunk") + 1))
        engine.start()
        try:
            long_p = rng.integers(1, cfg.vocab_size, 30).tolist()
            fut = engine.submit(long_p, max_new_tokens=4)
            assert fut.result(timeout=30.0) == _ref_greedy(
                params, cfg, long_p, 4)
            assert engine.metrics.engine_failures.value >= 1
        finally:
            engine.stop()


class TestTraceFailurePaths:
    """Trace-id + breakdown propagation through the FAILURE paths: the
    whole point of Dapper-style ids is answering "where did request X
    go" when it did NOT come back clean — so cancel, 504, watchdog
    stall, and supervised restart must all resolve with the id and the
    timing stamps intact."""

    def test_trace_survives_cancel(self, model):
        engine = _engine(model)
        fut = engine.submit([21, 22], max_new_tokens=30,
                            trace_id="tr-cancel")
        engine.step()
        engine.step()
        assert fut.cancel() is True
        engine.step()  # reclamation tick
        assert fut.done() and fut.finish_reason == "cancelled"
        assert fut.trace_id == "tr-cancel"
        b = fut.breakdown()
        assert b["finish"] == "cancelled"
        assert b["queue_wait_s"] >= 0 and b["prefill_s"] >= 0
        assert b["tokens"] == len(fut.result(timeout=0))
        assert b["total_s"] >= b["queue_wait_s"]

    def test_trace_survives_restart(self, model):
        """A mid-decode device fault: the doomed future resolves typed
        with its trace intact (error name in the breakdown), and the
        post-restart request traces independently."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise", skip=1)])
        engine = _engine(model, faults=inj, resume=False)
        doomed = engine.submit([3, 4, 5], max_new_tokens=8,
                               trace_id="tr-doomed")
        _run_until_done(engine, [doomed])
        with pytest.raises(serving.EngineFailedError):
            doomed.result(timeout=0)
        assert doomed.trace_id == "tr-doomed"
        b = doomed.breakdown()
        assert b["finish"] == "EngineFailedError"
        assert b["queue_wait_s"] is not None and b["total_s"] > 0
        fut = engine.submit([3, 4, 5], max_new_tokens=4,
                            trace_id="tr-after")
        _run_until_done(engine, [fut])
        assert fut.breakdown()["finish"] == "length"
        assert fut.trace_id == "tr-after"

    def test_trace_survives_watchdog_stall(self, model):
        """The watchdog resolves futures from ITS thread — the trace
        must be stamped there too, with the stall's typed error."""
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, n_slots=1, resume=False,
                         tick_timeout=0.3, watchdog_interval=0.02)
        _warm(engine)
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="hang", delay=1.2,
            skip=inj.visits("decode_tick") + 2))
        engine.start()
        try:
            f_run = engine.submit([11, 12, 13], max_new_tokens=30,
                                  trace_id="tr-stalled")
            # n_slots=1: this one stays QUEUED through the stall
            f_queued = engine.submit([14, 15], max_new_tokens=30,
                                     trace_id="tr-queued")
            for f in (f_run, f_queued):
                with pytest.raises(serving.EngineStalledError):
                    f.result(timeout=10.0)
            assert f_run.trace_id == "tr-stalled"
            assert f_run.breakdown()["finish"] == "EngineStalledError"
            # the queued one was never admitted: queue_wait covers its
            # whole life, prefill/decode stay None
            bq = f_queued.breakdown()
            assert bq["trace_id"] == "tr-queued"
            assert bq["finish"] == "EngineStalledError"
            assert bq["prefill_s"] is None
            assert bq["queue_wait_s"] == bq["total_s"]
        finally:
            engine.stop()

    @pytest.mark.slow
    def test_trace_survives_http_504(self, model):
        # Slow (PR 17 budget pass): ~5 s; test_trace_survives_watchdog
        # _stall keeps the trace-through-failure property tier-1 and
        # the 504 path itself is covered by test_504_cancels_and_
        # frees_slot.
        """The 504-timeout path: the client's X-Trace-Id comes back on
        the error payload with the partial breakdown, and the engine's
        cancel keeps the id through slot reclamation."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="hang",
                              delay=0.05, max_fires=None)])
        engine = _engine(model, faults=inj, n_slots=2)
        _warm(engine)
        with serving.ServingServer(engine, port=0, request_timeout=0.4,
                                   timeout_grace=0.1) as srv:
            host, port = srv.address
            req = urllib.request.Request(
                f"http://{host}:{port}/generate",
                data=json.dumps({"tokens": [1, 2], "max_new_tokens": 38,
                                 "timeout_ms": 60000}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Trace-Id": "tr-504"})
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 504")
            except urllib.error.HTTPError as e:
                assert e.code == 504
                out = json.loads(e.read())
                hdr = e.headers["X-Trace-Id"]
            assert out["type"] == "timeout"
            assert out["trace_id"] == hdr == "tr-504"
            assert out["breakdown"]["trace_id"] == "tr-504"
            assert out["breakdown"]["total_s"] > 0
            assert _wait_for(lambda: engine.slots.active_count == 0,
                             timeout=2.0)

    def test_metrics_endpoint_valid_during_failure(self, model):
        """GOLDEN: /metrics still parses as valid Prometheus text on a
        terminally failed engine, and the failure counters are
        visible in the scrape."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise",
                              max_fires=None)])
        engine = _engine(model, faults=inj, max_restarts=0)
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            base = f"http://{host}:{port}"
            code, out = _post(base + "/generate",
                              {"tokens": [1, 2], "max_new_tokens": 4})
            assert code == 503
            assert _wait_for(lambda: engine.health == "failed")
            with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
                fams = parse_prometheus_text(r.read().decode())
            assert fams["serving_engine_failures_total"][
                "samples"][0][2] >= 1
            assert "serving_ttft_seconds" in fams
            assert "elastic_restarts_total" in fams  # default registry too


class TestServerFaultTolerance:
    def _serve(self, engine, **kw):
        return serving.ServingServer(engine, port=0, **kw)

    def test_healthz_tracks_state_machine(self, model):
        """healthy -> 200; failed -> 503 (load balancers stop
        routing); stats carry the transition trail."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise",
                              max_fires=None)])
        engine = _engine(model, faults=inj, max_restarts=0)
        with self._serve(engine) as srv:
            host, port = srv.address
            base = f"http://{host}:{port}"
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as r:
                assert json.loads(r.read())["status"] == "healthy"
            code, out = _post(base + "/generate",
                              {"tokens": [1, 2], "max_new_tokens": 4})
            assert code == 503
            assert out["type"] == "engine_failed"
            assert _wait_for(lambda: engine.health == "failed")
            try:
                urllib.request.urlopen(base + "/healthz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["status"] == "failed"
            with urllib.request.urlopen(base + "/stats", timeout=10) as r:
                s = json.loads(r.read())
            assert s["state"] == "failed"
            assert s["engine_failures"] >= 1

    def test_504_cancels_and_frees_slot(self, model):
        """The 504 slot-leak fix: an HTTP timeout cancels the request,
        so the slot frees on the next tick instead of decoding to
        max_new_tokens for a caller that already got its error page."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="hang",
                              delay=0.05, max_fires=None)])
        engine = _engine(model, faults=inj, n_slots=2)
        _warm(engine)
        # explicit timeout_ms >> request_timeout: the engine deadline
        # never fires, so only the HTTP timeout (and its cancel) can
        # free the slot.
        with self._serve(engine, request_timeout=0.4,
                         timeout_grace=0.1) as srv:
            host, port = srv.address
            t0 = time.monotonic()
            code, out = _post(
                f"http://{host}:{port}/generate",
                {"tokens": [1, 2], "max_new_tokens": 38,
                 "timeout_ms": 60000})
            assert (code, out["type"]) == (504, "timeout")
            # 38 tokens x >=50ms/tick ~= 2s of decoding left; the
            # cancel must free the slot in ~one tick instead.
            assert _wait_for(lambda: engine.slots.active_count == 0,
                             timeout=1.0)
            assert time.monotonic() - t0 < 1.8
            assert engine.stats()["requests_cancelled"] == 1

    @pytest.mark.slow
    def test_default_deadline_from_request_timeout(self, model):
        # Slow (PR 17 budget pass): ~5 s; test_deadline_survives_resume
        # keeps deadline plumbing tier-1 end to end.
        """No client timeout_ms: the engine deadline defaults to the
        server's request_timeout, so the request deadline-retires with
        a partial result instead of running to max_new_tokens."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="hang",
                              delay=0.05, max_fires=None)])
        engine = _engine(model, faults=inj, n_slots=2)
        _warm(engine)
        with self._serve(engine, request_timeout=0.4) as srv:
            host, port = srv.address
            code, out = _post(f"http://{host}:{port}/generate",
                              {"tokens": [1, 2], "max_new_tokens": 38})
            assert code == 200
            assert out["finish_reason"] == "deadline"
            assert 1 <= len(out["tokens"]) < 38

    def test_drain_under_load(self, model):
        """stop(drain_timeout): a burst in flight completes, new
        requests get 503 draining, /healthz goes non-200, and teardown
        lands inside the budget."""
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="hang",
                              delay=0.03, max_fires=None)])
        engine = _engine(model, faults=inj, n_slots=4)
        _warm(engine)
        warm_admitted = engine.metrics.admitted.value
        srv = self._serve(engine, request_timeout=60.0).start()
        host, port = srv.address
        base = f"http://{host}:{port}"

        results = [None] * 6
        def client(i):
            results[i] = _post(base + "/generate",
                               {"tokens": [1 + i, 2 + i],
                                "max_new_tokens": 12})
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        # every client is IN the system (admitted or queued) before the
        # drain starts — none may be shed as 503 by a racing stop()
        # (admissions counted relative to the warm-up's)
        assert _wait_for(lambda: engine.metrics.admitted.value
                         - warm_admitted
                         + engine.scheduler.depth >= 6)

        t0 = time.monotonic()
        stopper = threading.Thread(target=lambda: srv.stop(
            drain_timeout=20.0))
        stopper.start()
        assert _wait_for(lambda: engine.health == "draining")
        # burst still decoding (>=8 ticks x 30ms left): probe the
        # draining server while it is provably mid-drain
        code, out = _post(base + "/generate", {"tokens": [9],
                                               "max_new_tokens": 2})
        assert (code, out["type"]) == (503, "draining")
        try:
            urllib.request.urlopen(base + "/healthz", timeout=10)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "draining"
        stopper.join(25.0)
        assert not stopper.is_alive()
        assert time.monotonic() - t0 < 22.0  # teardown inside budget
        for t in threads:
            t.join(10.0)
        # every admitted request completed normally through the drain
        assert all(r is not None and r[0] == 200
                   and r[1]["finish_reason"] == "length"
                   for r in results)
        assert engine.slots.active_count == 0
        assert engine.scheduler.depth == 0

    @pytest.mark.slow
    def test_chaos_soak_http(self, model):
        """Long soak: rolling faults under concurrent HTTP traffic;
        every response is 200 or a typed error payload, and the engine
        ends healthy and oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector([
            serving.FaultSpec(site="decode_tick", kind="raise",
                              skip=9, max_fires=3, p=0.5),
            serving.FaultSpec(site="prefill", kind="raise",
                              skip=12, max_fires=2, p=0.5),
        ], seed=11)
        engine = _engine(model, faults=inj, n_slots=4, max_restarts=50)
        _warm(engine, prompt_lens=(3, 7))
        rng = np.random.default_rng(13)
        with self._serve(engine, request_timeout=30.0) as srv:
            host, port = srv.address
            base = f"http://{host}:{port}"
            results = [None] * 32

            def client(i):
                p = rng.integers(0, cfg.vocab_size, 2 + i % 6).tolist()
                results[i] = _post(base + "/generate",
                                   {"tokens": p, "max_new_tokens":
                                    2 + i % 8}, timeout=60.0)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(32)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(90.0)
            assert all(r is not None for r in results)  # nothing hung
            assert all(r[0] in (200, 429, 503, 504) for r in results)
            assert _wait_for(lambda: engine.health == "healthy")
            prompt = [40, 41]
            code, out = _post(base + "/generate",
                              {"tokens": prompt, "max_new_tokens": 6})
            assert code == 200
            assert out["tokens"] == _ref_greedy(params, cfg, prompt, 6)


@pytest.mark.slow
class TestTunerResetOnRecover:
    """Regression (docs/serving.md "Self-tuning"): a supervised
    restart must DROP the online tuner's scoring-window baseline.
    The baseline predates the crash, so scoring the first post-restart
    window against it would charge the dead time + resume re-prefills
    to whatever knob setting happened to be live — garbage that can
    trip a spurious SLO rollback.  Slow (an autotune engine's full
    warm sweep); tier-1 siblings: test_tuning.py's
    test_reset_window_drops_baseline covers the reset itself, and
    TestSupervisedRestart here covers the _recover path every run."""

    def test_recover_resets_tuner_window(self, model):
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(model, faults=inj, autotune=True)
        _warm(engine)                      # installs the tuner
        tuner = engine._tuner
        assert tuner is not None
        # a couple of worked ticks so a window baseline is OPEN
        fut = engine.submit([9, 10], max_new_tokens=4)
        _run_until_done(engine, [fut])
        assert tuner._window is not None
        resets = []
        orig = tuner.reset_window
        tuner.reset_window = lambda: (resets.append(1), orig())[-1]
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="raise",
            skip=inj.visits("decode_tick") + 1))
        futs = [engine.submit([3, 4, 5], max_new_tokens=8)]
        _run_until_done(engine, futs)
        assert engine.stats()["engine_restarts"] == 1
        assert resets, "_recover never reset the tuner window"
        # recovery still serves the oracle, and the resumed request's
        # output is byte-identical through the restart
        assert futs[0].result(timeout=0) == _ref_greedy(
            params, cfg, [3, 4, 5], 8)
        fut = engine.submit([6, 7], max_new_tokens=6)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [6, 7], 6)
