"""MXNet frontend tests against a mocked ``mxnet`` module.

MXNet is not installable in this image (documented gate in
``horovod_tpu/mxnet/__init__.py``), so these tests install a minimal
interface-faithful stand-in — NDArray with ``asnumpy``/in-place slice
assignment/``wait_to_read``, ``optimizer.Optimizer``, ``gluon.Trainer``,
``gluon.parameter.ParameterDict`` with deferred init — and drive the real
frontend logic through it (the reference exercises ``test_mxnet.py``
against the real library under mpirun; the frontend code path is the
same either way since collectives cross at numpy)."""

import sys
import types as pytypes

import numpy as np
import pytest


class FakeNDArray:
    def __init__(self, arr):
        self._arr = np.array(arr, dtype=np.float32)

    def asnumpy(self):
        return self._arr.copy()

    def __setitem__(self, key, value):
        self._arr[key] = np.asarray(value)

    def wait_to_read(self):
        pass

    @property
    def shape(self):
        return self._arr.shape


class FakeOptimizer:
    def __init__(self, learning_rate=0.1, rescale_grad=1.0):
        self.learning_rate = learning_rate
        self.rescale_grad = rescale_grad
        self.updates = []

    def update(self, index, weight, grad, state):
        if isinstance(index, (tuple, list)):  # real mx handles both forms
            self.updates.append((index, [g.asnumpy().copy() for g in grad]))
            for w, g in zip(weight, grad):
                w[:] = w.asnumpy() - self.learning_rate * (
                    self.rescale_grad * g.asnumpy())
            return
        self.updates.append((index, grad.asnumpy().copy()))
        weight[:] = weight.asnumpy() - self.learning_rate * (
            self.rescale_grad * grad.asnumpy())

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def create_state_multi_precision(self, index, weight):
        return None

    def set_learning_rate(self, lr):
        self.learning_rate = lr

    def set_lr_mult(self, m):
        self.lr_mult = m

    def set_wd_mult(self, m):
        self.wd_mult = m


class FakeTrainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        self._params = list(params.values()) if isinstance(params, dict) \
            else list(params)
        self._optimizer = optimizer
        self._scale = 1.0
        assert kvstore is None


class DeferredInitializationError(Exception):
    pass


class FakeParameter:
    def __init__(self, name, data=None):
        self.name = name
        self.grad_req = "write"
        self._data = None if data is None else FakeNDArray(data)
        self._grad = FakeNDArray(np.zeros(3))
        self.init_calls = []

    def data(self):
        if self._data is None:
            raise DeferredInitializationError(self.name)
        return self._data

    def list_grad(self):
        return [self._grad]

    def _init_impl(self, *a, **kw):
        self._data = FakeNDArray(np.arange(3, dtype=np.float32))
        self.init_calls.append(a)


class FakeParameterDict:
    def __init__(self, params):
        self._params = dict(params)

    def items(self):
        return self._params.items()


@pytest.fixture(scope="module")
def hvd_mx():
    """Install the mock and import the frontend through it."""
    mx = pytypes.ModuleType("mxnet")
    mx.nd = pytypes.SimpleNamespace(array=FakeNDArray)
    mx.optimizer = pytypes.SimpleNamespace(Optimizer=FakeOptimizer)
    mx.gluon = pytypes.SimpleNamespace(
        Trainer=FakeTrainer,
        parameter=pytypes.SimpleNamespace(
            ParameterDict=FakeParameterDict,
            DeferredInitializationError=DeferredInitializationError,
        ),
    )
    saved_mx = sys.modules.get("mxnet")
    saved_frontend = sys.modules.pop("horovod_tpu.mxnet", None)
    sys.modules["mxnet"] = mx
    try:
        import horovod_tpu.mxnet as hvd_mx

        yield hvd_mx
    finally:
        if saved_mx is not None:
            sys.modules["mxnet"] = saved_mx
        else:
            sys.modules.pop("mxnet", None)
        if saved_frontend is not None:
            sys.modules["horovod_tpu.mxnet"] = saved_frontend
        else:
            sys.modules.pop("horovod_tpu.mxnet", None)


class TestOps:
    def test_allreduce_returns_ndarray(self, hvd, hvd_mx):
        x = FakeNDArray([1.0, 2.0])
        out = hvd_mx.allreduce(x, average=True)
        assert isinstance(out, FakeNDArray)
        np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])  # size 1

    def test_allreduce_inplace(self, hvd, hvd_mx):
        x = FakeNDArray([3.0, 4.0])
        ret = hvd_mx.allreduce_(x, average=False)
        assert ret is x
        # average=False is a chip-weighted Sum (docs/concepts.md).
        ls = hvd.local_size()
        np.testing.assert_allclose(x.asnumpy(), [3.0 * ls, 4.0 * ls])

    def test_broadcast_inplace(self, hvd, hvd_mx):
        x = FakeNDArray([5.0])
        hvd_mx.broadcast_(x, root_rank=0)
        np.testing.assert_allclose(x.asnumpy(), [5.0])


class TestDistributedOptimizer:
    def test_rescale_grad_divided_by_size(self, hvd, hvd_mx):
        base = FakeOptimizer(rescale_grad=2.0)
        opt = hvd_mx.DistributedOptimizer(base)
        assert base.rescale_grad == pytest.approx(2.0 / hvd_mx.cross_size())

    def test_deepcopy_does_not_recurse(self, hvd, hvd_mx):
        # deepcopy probes __deepcopy__ before __init__ runs on the copy;
        # __getattr__ must not recurse on the missing _optimizer
        import copy

        opt = hvd_mx.DistributedOptimizer(FakeOptimizer(rescale_grad=1.0))
        clone = copy.deepcopy(opt)
        assert clone._optimizer.rescale_grad == opt._optimizer.rescale_grad

    def test_update_delegates_and_reduces(self, hvd, hvd_mx):
        base = FakeOptimizer(learning_rate=0.5, rescale_grad=1.0)
        opt = hvd_mx.DistributedOptimizer(base)
        w = FakeNDArray([1.0, 1.0])
        g = FakeNDArray([0.2, 0.2])
        opt.update(0, w, g, None)
        assert base.updates and base.updates[0][0] == 0
        np.testing.assert_allclose(w.asnumpy(), [0.9, 0.9])

    def test_update_list_indices(self, hvd, hvd_mx):
        base = FakeOptimizer()
        opt = hvd_mx.DistributedOptimizer(base)
        ws = [FakeNDArray([1.0]), FakeNDArray([2.0])]
        gs = [FakeNDArray([0.1]), FakeNDArray([0.2])]
        opt.update([0, 1], ws, gs, [None, None])
        # FakeOptimizer.update receives the list as-is
        assert base.updates[0][0] == [0, 1]

    def test_getattr_delegation(self, hvd, hvd_mx):
        base = FakeOptimizer(learning_rate=0.25)
        opt = hvd_mx.DistributedOptimizer(base)
        assert opt.learning_rate == 0.25
        opt.set_learning_rate(0.5)
        assert base.learning_rate == 0.5


class TestDistributedTrainer:
    def test_scale_divided_and_unwrap(self, hvd, hvd_mx):
        base = FakeOptimizer()
        wrapped = hvd_mx.DistributedOptimizer(base)
        p = FakeParameter("w0", data=[1.0, 1.0, 1.0])
        tr = hvd_mx.DistributedTrainer({"w0": p}, wrapped)
        assert tr._optimizer is base  # unwrapped, reference behavior
        assert tr._scale == pytest.approx(1.0 / hvd_mx.cross_size())
        tr._allreduce_grads()  # size 1: no-op, must not raise


class TestBroadcastParameters:
    def test_dict_of_ndarrays(self, hvd, hvd_mx):
        params = {"a": FakeNDArray([1.0]), "b": FakeNDArray([2.0])}
        hvd_mx.broadcast_parameters(params)  # size 1: no-op

    def test_parameter_dict_with_deferred_init(self, hvd, hvd_mx,
                                               monkeypatch):
        # Force the multi-worker path so the deferred hook is installed.
        monkeypatch.setattr(hvd_mx, "cross_size", lambda: 2)
        calls = []
        monkeypatch.setattr(
            hvd_mx, "broadcast_",
            lambda t, root_rank=0, name=None: calls.append(name) or t)
        ready = FakeParameter("w0", data=[1.0, 2.0, 3.0])
        deferred = FakeParameter("w1")  # no data yet
        pd = FakeParameterDict({"w0": ready, "w1": deferred})
        hvd_mx.broadcast_parameters(pd, root_rank=0)
        assert calls == ["param.0"]  # only the ready one broadcast now
        # deferred param broadcasts as soon as init runs
        deferred._init_impl()
        assert len(calls) == 2
        assert deferred._data is not None

    def test_invalid_type_raises(self, hvd, hvd_mx):
        monkey = lambda: 2
        orig = hvd_mx.cross_size
        hvd_mx.cross_size = monkey
        try:
            with pytest.raises(ValueError, match="invalid params"):
                hvd_mx.broadcast_parameters([1, 2, 3])
        finally:
            hvd_mx.cross_size = orig
