"""True pipeline parallelism (GPipe microbatching over pp via ppermute):
outputs and gradients must match plain sequential layer application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd  # noqa: F401 — device count setup via conftest
from horovod_tpu.parallel import pipeline

NDEV = 8


def _mesh(p):
    return Mesh(np.array(jax.devices()[:p]), axis_names=("pp",))


def _stage_fn(w_stack, x):
    """One stage = a scan over this stage's layer weights (tanh MLP)."""
    def layer(h, w):
        return jnp.tanh(h @ w), None

    out, _ = jax.lax.scan(layer, x, w_stack)
    return out


def _assert_grad_trees_match(g, g_ref, *, atol=2e-4, rtol=2e-4):
    """Leaf-for-leaf gradient comparison with path-keyed lookup and a
    structure check (zip would silently truncate on tree mismatch)."""
    flat_pipe = dict(jax.tree_util.tree_leaves_with_path(g))
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    assert set(flat_pipe) == {p for p, _ in flat_ref}
    for path, ref_leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_pipe[path]), np.asarray(ref_leaf),
            atol=atol, rtol=rtol, err_msg=jax.tree_util.keystr(path))


EXPERT_KEYS = ("w_gate", "w_up", "w_down")


def _ep_shard_params(pr, n_experts, ep):
    """Slice this device's resident experts out of the replicated stacks
    (layer layout ``(L, E, ...)``, experts on axis 1)."""
    e = jax.lax.axis_index("ep")
    e_loc = n_experts // ep
    return {**pr, "layers": {
        k: (jax.lax.dynamic_slice_in_dim(v, e * e_loc, e_loc, 1)
            if k in EXPERT_KEYS else v)
        for k, v in pr["layers"].items()}}


def _ep_unshard_grads(grads, n_experts, ep):
    """Reassemble full-model grads from ep-resident pieces: resident-
    expert grads are COMPLETE (every token's cotangent returns through
    the all_to_all), so psum assembles the stack and /ep matches the
    pmean-over-ep loss scaling applied to the non-expert params."""
    e = jax.lax.axis_index("ep")
    e_loc = n_experts // ep

    def unshard(k, gv):
        if k in EXPERT_KEYS:
            full = jnp.zeros((gv.shape[0], n_experts) + gv.shape[2:],
                             gv.dtype)
            full = jax.lax.dynamic_update_slice_in_dim(full, gv,
                                                       e * e_loc, 1)
            return jax.lax.psum(full, "ep") / ep
        return jax.lax.pmean(gv, "ep")

    lg = {k: unshard(k, v) for k, v in grads["layers"].items()}
    return {**{k: jax.tree_util.tree_map(
        lambda x: jax.lax.pmean(x, "ep"), v)
        for k, v in grads.items() if k != "layers"}, "layers": lg}


def _sequential(w_all, x):
    def layer(h, w):
        return jnp.tanh(h @ w), None

    out, _ = jax.lax.scan(layer, x, w_all)
    return out


class TestPipelineApply:
    @pytest.mark.parametrize("p,layers,m", [(4, 8, 4), (8, 8, 2), (2, 6, 5)])
    def test_matches_sequential(self, p, layers, m):
        d = 16
        key = jax.random.PRNGKey(0)
        w_all = jax.random.normal(key, (layers, d, d)) * (0.5 / np.sqrt(d))
        mb = 3
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))

        staged = pipeline.stack_to_stages(w_all, p)
        mesh = _mesh(p)

        def run(staged, x):
            def inner(wst, xs):
                return pipeline.pipeline_apply(
                    _stage_fn, wst[0], xs, axis_name="pp")

            return jax.jit(jax.shard_map(
                inner, mesh=mesh,
                in_specs=(P("pp"), P()),
                out_specs=P(),
            ))(staged, x)

        out = run(staged, x)
        ref = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match_sequential(self):
        p, layers, m, mb, d = 4, 8, 4, 2, 8
        w_all = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))
        mesh = _mesh(p)

        def loss_pipe(w_all, x):
            staged = pipeline.stack_to_stages(w_all, p)

            def inner(wst, xs):
                out = pipeline.pipeline_apply(
                    _stage_fn, wst[0], xs, axis_name="pp")
                return jnp.sum(out ** 2)

            return jax.shard_map(
                inner, mesh=mesh, in_specs=(P("pp"), P()),
                out_specs=P(),
            )(staged, x)

        def loss_seq(w_all, x):
            out = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
            return jnp.sum(out ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(w_all, x)
        g_seq = jax.grad(loss_seq)(w_all, x)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                                   atol=1e-4, rtol=1e-4)

    def test_indivisible_layers_raise(self):
        w_all = jnp.zeros((7, 4, 4))
        with pytest.raises(ValueError, match="divide"):
            pipeline.stack_to_stages(w_all, 4)


class TestInterleavedApply:
    @pytest.mark.parametrize("p,v,layers,m", [(4, 2, 8, 4), (2, 3, 6, 4),
                                              (4, 1, 4, 8)])
    def test_matches_sequential(self, p, v, layers, m):
        """The virtual-stage schedule must be a pure re-scheduling: same
        outputs as sequential application, for v in {1, 2, 3}."""
        d = 16
        w_all = jax.random.normal(
            jax.random.PRNGKey(0), (layers, d, d)) * (0.5 / np.sqrt(d))
        x = jax.random.normal(jax.random.PRNGKey(1), (m, 3, d))
        mesh = _mesh(p)

        def inner(w_full, xs):
            s = jax.lax.axis_index("pp")
            chunks = pipeline.stack_to_chunks(w_full, p, v, s)
            return pipeline.interleaved_apply(
                _stage_fn, chunks, xs, axis_name="pp", n_virtual=v)

        out = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        ))(w_all, x)
        ref = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match_sequential(self):
        p, v, layers, m, mb, d = 4, 2, 8, 4, 2, 8
        w_all = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))
        mesh = _mesh(p)

        def loss_pipe(w_all, x):
            def inner(w_full, xs):
                s = jax.lax.axis_index("pp")
                chunks = pipeline.stack_to_chunks(w_full, p, v, s)
                out = pipeline.interleaved_apply(
                    _stage_fn, chunks, xs, axis_name="pp", n_virtual=v)
                # Gate to the last chunk's device so the replicated-stack
                # VJP psum sums one real contribution with zeros.
                raw = jnp.sum(out ** 2)
                return jax.lax.psum(
                    jnp.where(s == p - 1, raw, 0.0), "pp")

            return jax.shard_map(
                inner, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            )(w_all, x)

        def loss_seq(w_all, x):
            out = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
            return jnp.sum(out ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(w_all, x)
        g_seq = jax.grad(loss_seq)(w_all, x)
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                                   atol=1e-4, rtol=1e-4)

    def test_microbatch_divisibility_enforced(self):
        mesh = _mesh(4)
        w = jnp.zeros((8, 4, 4))
        x = jnp.zeros((6, 2, 4))  # 6 % 4 != 0

        def inner(w_full, xs):
            s = jax.lax.axis_index("pp")
            chunks = pipeline.stack_to_chunks(w_full, 4, 2, s)
            return pipeline.interleaved_apply(
                _stage_fn, chunks, xs, axis_name="pp", n_virtual=2)

        with pytest.raises(ValueError, match="divisible"):
            jax.jit(jax.shard_map(
                inner, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            ))(w, x)


def _loss_fn(y, tgt):
    return jnp.sum((y - tgt) ** 2)


class TestPipeline1F1B:
    def _run_schedule(self, schedule, p, layers, m, mb=2, d=8):
        w_all = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))
        tgt = jax.random.normal(jax.random.PRNGKey(2), (m, mb, d)) * 0.1
        staged = pipeline.stack_to_stages(w_all, p)
        mesh = _mesh(p)

        def inner(wst, xs, ts):
            loss, g = pipeline.pipeline_value_and_grad(
                _stage_fn, wst[0], xs, ts, _loss_fn, axis_name="pp",
                schedule=schedule)
            return loss, g[None]

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp")),
        ))
        loss, g = fn(staged, x, tgt)
        return w_all, x, tgt, float(loss), np.asarray(g).reshape(w_all.shape)

    @pytest.mark.parametrize("p,layers,m", [(4, 8, 6), (2, 6, 5), (8, 8, 3)])
    def test_1f1b_exact_vs_sequential_and_gpipe(self, p, layers, m):
        """1F1B loss and EVERY stage gradient must match both the GPipe
        schedule and plain sequential autodiff."""
        w_all, x, tgt, loss_1, g_1 = self._run_schedule("1f1b", p, layers, m)

        def loss_seq(w_all):
            outs = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
            return jnp.sum(jax.vmap(_loss_fn)(outs, tgt))

        l_ref, g_ref = jax.value_and_grad(loss_seq)(w_all)
        np.testing.assert_allclose(loss_1, float(l_ref), rtol=1e-5)
        np.testing.assert_allclose(g_1, np.asarray(g_ref),
                                   atol=1e-4, rtol=1e-4)

        _, _, _, loss_g, g_g = self._run_schedule("gpipe", p, layers, m)
        np.testing.assert_allclose(loss_1, loss_g, rtol=1e-5)
        np.testing.assert_allclose(g_1, g_g, atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("with_lp,with_xg", [
        (True, True), (True, False), (False, True)])
    def test_loss_params_and_input_grads_exact(self, schedule, with_lp,
                                               with_xg):
        """loss_params (readout head) gradients and input cotangents from
        BOTH schedules must match direct autodiff — including the VMA
        subtlety that the VJP of a replicated operand inside shard_map
        implicitly psums over the axis (regression for the bug where
        non-last stages' garbage loss grads leaked into the sum)."""
        p, layers, m, mb, d = 4, 8, 6, 2, 8
        w_all = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (m, mb, d))
        tgt = jax.random.normal(jax.random.PRNGKey(2), (m, mb, d)) * 0.1
        head = jax.random.normal(jax.random.PRNGKey(3), (d, d)) * 0.5

        def lfn_lp(lp, y, t):
            return jnp.sum((y @ lp["head"] - t) ** 2)

        def lfn_plain(y, t):
            return lfn_lp({"head": head}, y, t)

        def ref():
            def loss(w_all, head, x):
                outs = jax.vmap(lambda xb: _sequential(w_all, xb))(x)
                return jnp.sum(jax.vmap(
                    lambda y, t: lfn_lp({"head": head}, y, t))(outs, tgt))

            return jax.value_and_grad(loss, argnums=(0, 1, 2))(w_all, head, x)

        staged = pipeline.stack_to_stages(w_all, p)
        mesh = _mesh(p)

        def inner(wst, xs, ts, lp):
            loss, g, ex = pipeline.pipeline_value_and_grad(
                _stage_fn, wst[0], xs, ts,
                lfn_lp if with_lp else lfn_plain, axis_name="pp",
                schedule=schedule,
                loss_params=lp if with_lp else None,
                return_input_grads=with_xg)
            lpg = (jax.tree_util.tree_map(
                lambda a: jax.lax.psum(a, "pp"), ex["loss_param_grads"])
                if with_lp else {"head": jnp.zeros_like(lp["head"])})
            xg = (jax.lax.psum(ex["input_grads"], "pp")
                  if with_xg else jnp.zeros_like(xs))
            assert set(ex) == ({"loss_param_grads"} if with_lp else set()) | (
                {"input_grads"} if with_xg else set())
            return loss, g[None], lpg, xg

        loss, g, lpg, xg = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P("pp"), P(), P())))(staged, x, tgt,
                                                 {"head": head})
        l_ref, (gw_ref, gh_ref, gx_ref) = ref()
        np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
        if with_lp:
            np.testing.assert_allclose(
                np.asarray(lpg["head"]), np.asarray(gh_ref),
                atol=1e-5, rtol=1e-5)
        if with_xg:
            np.testing.assert_allclose(np.asarray(xg), np.asarray(gx_ref),
                                       atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g).reshape(w_all.shape), np.asarray(gw_ref),
            atol=1e-5, rtol=1e-5)

    def test_unknown_schedule_raises(self):
        mesh = _mesh(2)
        w = jnp.zeros((2, 1, 4, 4))
        x = jnp.zeros((2, 1, 4))
        t = jnp.zeros((2, 1, 4))
        with pytest.raises(ValueError, match="schedule"):
            jax.shard_map(
                lambda wst, xs, ts: pipeline.pipeline_value_and_grad(
                    _stage_fn, wst[0], xs, ts, _loss_fn, axis_name="pp",
                    schedule="bogus"),
                mesh=mesh, in_specs=(P("pp"), P(), P()),
                out_specs=(P(), P("pp")),
            )(w, x, t)

    def test_1f1b_memory_independent_of_m(self):
        """The 1F1B claim, MEASURED: raising M (16 vs 4) must leave the
        1F1B temp footprint ~flat (in-flight state is bounded by 2(P-1)
        stage inputs), while GPipe's autodiff footprint grows with M.
        Uses XLA's compiled memory analysis at M=16, P=4."""
        p, layers, mb, d = 4, 8, 8, 64

        def compiled_temp_bytes(schedule, m):
            w_all = jnp.zeros((layers, d, d))
            x = jnp.zeros((m, mb, d))
            tgt = jnp.zeros((m, mb, d))
            staged = pipeline.stack_to_stages(w_all, p)
            mesh = _mesh(p)

            def inner(wst, xs, ts):
                loss, g = pipeline.pipeline_value_and_grad(
                    _stage_fn, wst[0], xs, ts, _loss_fn, axis_name="pp",
                    schedule=schedule)
                return loss, g[None]

            fn = jax.jit(jax.shard_map(
                inner, mesh=mesh, in_specs=(P("pp"), P(), P()),
                out_specs=(P(), P("pp"))))
            c = fn.lower(staged, x, tgt).compile()
            return c.memory_analysis().temp_size_in_bytes

        gpipe_4 = compiled_temp_bytes("gpipe", 4)
        gpipe_16 = compiled_temp_bytes("gpipe", 16)
        f1b_4 = compiled_temp_bytes("1f1b", 4)
        f1b_16 = compiled_temp_bytes("1f1b", 16)

        # GPipe: autodiff saves every tick's residuals -> grows with M.
        assert gpipe_16 > gpipe_4 * 2, (gpipe_4, gpipe_16)
        # 1F1B: in-flight state bounded by pipeline depth, not M.  Allow
        # slack for the (M-proportional) microbatch INPUT buffers that any
        # schedule carries.
        assert f1b_16 < f1b_4 * 2, (f1b_4, f1b_16)
        # And at the benchmark point (M=16, P=4) 1F1B must be the smaller
        # footprint.
        assert f1b_16 < gpipe_16, (f1b_16, gpipe_16)


class TestPipelinedTransformerAPI:
    def _setup(self, p=4):
        from horovod_tpu.models import transformer as T

        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64,
            max_seq=16, dtype=jnp.float32)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(1, cfg, batch=4)
        return T, cfg, params, batch

    def test_forward_matches(self):
        p = 4
        T, cfg, params, batch = self._setup(p)
        ref = T.forward(params, batch["tokens"], cfg)
        mesh = _mesh(p)

        out = jax.jit(jax.shard_map(
            lambda pr, tk: T.pipelined_forward(pr, tk, cfg),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        ))(params, batch["tokens"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "interleaved"])
    @pytest.mark.slow
    def test_value_and_grad_exact(self, schedule):
        """The pipelined loss AND every parameter gradient — embedding,
        per-layer, final norm, head — must equal jax.grad(loss_fn), for
        ALL THREE schedules (interleaved runs v=2 virtual stages)."""
        p = 4
        T, cfg, params, batch = self._setup(p)
        l_ref, g_ref = jax.value_and_grad(
            lambda pr: T.loss_fn(pr, batch, cfg))(params)
        mesh = _mesh(p)

        l_pipe, g_pipe = jax.jit(jax.shard_map(
            lambda pr, b: T.pipelined_value_and_grad(
                pr, b, cfg, schedule=schedule),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        ))(params, batch)
        np.testing.assert_allclose(float(l_pipe), float(l_ref), atol=1e-5)
        _assert_grad_trees_match(g_pipe, g_ref)

    def _moe_setup(self, p=4):
        import dataclasses

        from horovod_tpu.models import transformer as T

        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64,
            max_seq=16, dtype=jnp.float32, n_experts=4,
            capacity_factor=4.0,  # dropless: exactness vs loss_fn holds
            moe_aux_coeff=0.02)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(1, cfg, batch=4)
        return dataclasses, T, cfg, params, batch

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    @pytest.mark.slow
    def test_moe_aux_value_and_grad_exact_m1(self, schedule):
        """With ONE microbatch the pipelined dispatch group equals the
        full batch, so the aux-bearing pipelined loss and every gradient
        (router included — the leaf only the aux term can reach evenly)
        must equal jax.grad of the aux-bearing loss_fn."""
        p = 4
        dataclasses, T, cfg, params, batch = self._moe_setup(p)
        l_ref, g_ref = jax.value_and_grad(
            lambda pr: T.loss_fn(pr, batch, cfg))(params)
        mesh = _mesh(p)

        l_pipe, g_pipe = jax.jit(jax.shard_map(
            lambda pr, b: T.pipelined_value_and_grad(
                pr, b, cfg, schedule=schedule, n_microbatches=1),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        ))(params, batch)
        np.testing.assert_allclose(float(l_pipe), float(l_ref), atol=1e-5)
        _assert_grad_trees_match(g_pipe, g_ref)

    @pytest.mark.slow
    def test_moe_aux_schedules_agree_and_reach_router(self):
        """For M>1 the aux is per dispatch group (mean over groups): the
        two schedules must agree with each other exactly, and the aux
        term must actually move the router gradient vs coeff=0."""
        p = 4
        dataclasses, T, cfg, params, batch = self._moe_setup(p)
        mesh = _mesh(p)

        def run(cfg_, schedule):
            return jax.jit(jax.shard_map(
                lambda pr, b: T.pipelined_value_and_grad(
                    pr, b, cfg_, schedule=schedule, n_microbatches=4),
                mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            ))(params, batch)

        l_g, g_g = run(cfg, "gpipe")
        l_f, g_f = run(cfg, "1f1b")
        np.testing.assert_allclose(float(l_g), float(l_f), atol=1e-5)
        _assert_grad_trees_match(g_g, g_f)

        cfg0 = dataclasses.replace(cfg, moe_aux_coeff=0.0)
        _, g_0 = run(cfg0, "1f1b")
        diff = np.abs(np.asarray(g_f["layers"]["router"])
                      - np.asarray(g_0["layers"]["router"])).max()
        assert diff > 1e-7, "aux term must reach the router gradient"


def _run_composition_worker(mode: str):
    """Spawn tests/composition_worker.py in a SUBPROCESS: the XLA CPU
    runtime's collective rendezvous accumulates state across the several
    distinct multi-axis meshes a full-suite process builds and aborts
    (each composition passes standalone in its own process — a backend
    limitation, not a framework one).  The worker shares the ep
    shard/unshard helpers and gradient assertions with this module."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "PYTHONPATH": repo,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    out = subprocess.run(
        [sys.executable,
         os.path.join(repo, "tests", "composition_worker.py"), mode],
        env=env, capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stdout + out.stderr
    assert f"COMPOSITION-{mode.upper()}-OK" in out.stdout, out.stdout


class TestPipelineCompositions:
    """1F1B composed with the other parallelism axes, each loss- and
    gradient-exact vs the unsharded single-device reference model (see
    composition_worker.py for the mesh arrangements)."""

    @pytest.mark.slow
    def test_1f1b_ring_attention_pp_x_sp_exact(self):
        """(pp, sp): ring K/V shards ppermute over sp within each
        pipeline stage while microbatch activations ppermute over pp."""
        _run_composition_worker("sp")

    @pytest.mark.slow
    def test_1f1b_switch_moe_pp_x_ep_exact(self):
        """(pp, ep): ep shards BOTH the batch (dp-style) and the experts
        — each device dispatches ITS tokens to resident experts via the
        all_to_all inside every stage."""
        _run_composition_worker("ep")

    @pytest.mark.slow
    def test_interleaved_ring_pp_x_sp_exact(self):
        """INTERLEAVED schedule (v=2 virtual stages) composed with ring
        attention over sp — the bubble-divided schedule is as composable
        as 1F1B."""
        _run_composition_worker("sp_interleaved")

    @pytest.mark.slow
    def test_1f1b_zigzag_ring_pp_x_sp_exact(self):
        """1F1B composed with the ZIGZAG (causal load-balanced) ring."""
        _run_composition_worker("sp_zigzag")

    @pytest.mark.slow
    def test_1f1b_ring_moe_pp_x_sp_x_ep_exact(self):
        """(pp, sp, ep): all three in one shard_map."""
        _run_composition_worker("triple")


class TestPipelineTransformerStage:
    def test_transformer_blocks_pipelined(self):
        """Pipeline the transformer's scanned layers: pp=4 stages of 2
        layers each must reproduce the plain forward."""
        import dataclasses

        from horovod_tpu.models import transformer as T

        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64,
            max_seq=16, dtype=jnp.float32)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        ref = T.forward(params, tokens, cfg)

        p = 4
        mesh = _mesh(p)
        x_emb = params["embed"][tokens]  # (B, S, D) pre-layer activations
        mb = jnp.reshape(x_emb, (4, 1) + x_emb.shape[1:])  # M=4, mb=1

        def stage_fn(stage_layers, x):
            def body(h, lp):
                h2 = T._attention(T._rmsnorm(h, lp["ln1"]), lp, cfg)
                h = h + h2
                return h + T._dense_mlp(T._rmsnorm(h, lp["ln2"]), lp, cfg), None

            out, _ = jax.lax.scan(body, x, stage_layers)
            return out

        staged = pipeline.stack_to_stages(params["layers"], p)

        def inner(wst, xs):
            mine = jax.tree_util.tree_map(lambda l: l[0], wst)
            return pipeline.pipeline_apply(stage_fn, mine, xs,
                                           axis_name="pp")

        out = jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P("pp"), P()),
            out_specs=P(),
        ))(staged, mb)
        out = jnp.reshape(out, x_emb.shape)
        out = T._rmsnorm(out, params["ln_f"])
        logits = jnp.einsum("bsd,dv->bsv", out, params["head"]).astype(
            jnp.float32)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)
