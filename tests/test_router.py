"""Replicated serving front tier (horovod_tpu/serving/router/).

Two layers of proof:

* **Unit** (fake replicas — tiny stdlib HTTP servers serving canned
  ``/stats`` and scriptable ``/generate`` behavior): the
  join-shortest-queue policy, rotation eviction (state / stale
  heartbeat / poll failure / proxy mark), retry-with-failover
  semantics, trace-id propagation, the ``Retry-After`` headers, and
  the ``/stats`` routing contract on a REAL engine.
* **Chaos** (real replica subprocesses, each a full engine + HTTP
  server): SIGKILL and FaultInjector-hang a replica mid-request under
  concurrent load and assert the front-tier invariant — 100% of
  submitted requests resolve with tokens or a typed error, ZERO
  drops, the router evicts within a poll, the supervisor respawns,
  and greedy output stays oracle-identical after failover.
"""

import json
import os
import signal
import socket
import tempfile
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving.router import (
    ReplicaEndpoint,
    ReplicaRegistry,
    ReplicaSpec,
    ReplicaSupervisor,
    RolloutController,
    RouterServer,
)
from horovod_tpu.serving.router.replica_main import parse_fault

pytestmark = pytest.mark.router


# ---------------------------------------------------------------------------
# fakes: a scriptable replica endpoint without an engine behind it
# ---------------------------------------------------------------------------


class _FakeReplica:
    """A stdlib HTTP server impersonating one replica: ``/stats``
    serves a mutable snapshot dict, ``/generate`` behavior is scripted
    per instance (``ok`` / ``drop`` / ``hang`` / an HTTP status)."""

    def __init__(self, rid, *, queue_depth=0, occupancy=0.0,
                 state="healthy", heartbeat=0.01):
        self.rid = rid
        self.stats = {"queue_depth": queue_depth, "occupancy": occupancy,
                      "engine_state": state, "heartbeat_age_s": heartbeat}
        self.mode = "ok"
        self.hang_s = 10.0
        self.seen_trace_ids = []
        self.seen_parent_spans = []
        self.seen_sampled = []
        self.seen_bodies = []
        self.generate_hits = 0
        self.reply_tokens = [1, 2, 3]
        self.resume_desc = None  # payload for mode="503resume"
        fake = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code, payload, headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/stats":
                    self._json(200, dict(fake.stats))
                else:
                    self._json(404, {})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                try:
                    fake.seen_bodies.append(json.loads(raw or b"{}"))
                except json.JSONDecodeError:
                    fake.seen_bodies.append(None)
                fake.generate_hits += 1
                fake.seen_trace_ids.append(
                    self.headers.get("X-Trace-Id"))
                fake.seen_parent_spans.append(
                    self.headers.get("X-Parent-Span"))
                fake.seen_sampled.append(
                    self.headers.get("X-Trace-Sampled"))
                if fake.mode == "drop":
                    # Die mid-request, SIGKILL-style: no status line,
                    # no body, just a dead socket.
                    self.connection.shutdown(socket.SHUT_RDWR)
                    self.connection.close()
                    return
                if fake.mode == "hang":
                    time.sleep(fake.hang_s)
                if fake.mode == "503":
                    self._json(503, {"error": "draining",
                                     "type": "draining"},
                               headers=[("Retry-After", "1")])
                    return
                if fake.mode == "429":
                    self._json(429, {"error": "queue full",
                                     "type": "queue_full"})
                    return
                if fake.mode == "503resume":
                    # A terminal engine failure mid-request: the typed
                    # 503 carries the resume descriptor, exactly like
                    # serving/server.py's engine_failed path.
                    self._json(503, {"error": "engine failed",
                                     "type": "engine_failed",
                                     "resume": fake.resume_desc})
                    return
                self._json(200, {"tokens": list(fake.reply_tokens),
                                 "finish_reason": "length",
                                 "served_by": fake.rid})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    @property
    def endpoint(self):
        host, port = self._httpd.server_address[:2]
        return ReplicaEndpoint(self.rid, host, port)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _registry(*fakes, **kw):
    kw.setdefault("poll_interval", 0.05)
    kw.setdefault("poll_timeout", 1.0)
    reg = ReplicaRegistry(**kw)
    for f in fakes:
        reg.add(f.endpoint)
    reg.poll_now()
    return reg


def _post(base, payload, headers=(), timeout=30):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **dict(headers)})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---------------------------------------------------------------------------
# registry: routing set + join-shortest-queue
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_jsq_picks_shortest_queue_then_occupancy(self):
        fakes = [_FakeReplica("a", queue_depth=5, occupancy=0.2),
                 _FakeReplica("b", queue_depth=1, occupancy=0.9),
                 _FakeReplica("c", queue_depth=1, occupancy=0.1)]
        reg = _registry(*fakes)
        try:
            assert reg.pick().endpoint.rid == "c"  # ties broken by occ
            fakes[2].stats["queue_depth"] = 7
            reg.poll_now()
            assert reg.pick().endpoint.rid == "b"
        finally:
            for f in fakes:
                f.stop()

    def test_jsq_round_robin_among_ties(self):
        fakes = [_FakeReplica("a"), _FakeReplica("b"), _FakeReplica("c")]
        reg = _registry(*fakes)
        try:
            picks = [reg.pick().endpoint.rid for _ in range(6)]
            # All equal load: every replica shares, none is dogpiled.
            assert sorted(set(picks)) == ["a", "b", "c"]
            assert picks[:3] != [picks[0]] * 3
        finally:
            for f in fakes:
                f.stop()

    def test_pick_excludes_tried_replicas(self):
        fakes = [_FakeReplica("a"), _FakeReplica("b")]
        reg = _registry(*fakes)
        try:
            assert reg.pick(exclude={"a", "b"}) is None
            assert reg.pick(exclude={"a"}).endpoint.rid == "b"
        finally:
            for f in fakes:
                f.stop()

    def test_nonroutable_states_leave_rotation(self):
        f = _FakeReplica("a")
        reg = _registry(f)
        try:
            assert reg.is_routable("a")
            for state in ("draining", "failed", "unknown"):
                f.stats["engine_state"] = state
                reg.poll_now()
                assert not reg.is_routable("a"), state
            f.stats["engine_state"] = "degraded"  # restarted = routable
            reg.poll_now()
            assert reg.is_routable("a")
            assert reg.metrics.replica_evictions.value == 1
        finally:
            f.stop()

    def test_stale_heartbeat_evicts(self):
        f = _FakeReplica("a", heartbeat=0.01)
        reg = _registry(f, heartbeat_stale=5.0)
        try:
            assert reg.is_routable("a")
            f.stats["heartbeat_age_s"] = 99.0  # engine stopped ticking
            reg.poll_now()
            assert not reg.is_routable("a")
        finally:
            f.stop()

    def test_never_ticked_gets_startup_grace_then_evicts(self):
        f = _FakeReplica("a", heartbeat=-1.0)
        reg = _registry(f, heartbeat_stale=5.0, startup_grace=0.2)
        try:
            assert reg.is_routable("a")  # warming, within grace
            time.sleep(0.25)
            assert not reg.is_routable("a")  # never ticked: wedged
        finally:
            f.stop()

    def test_poll_failures_evict_after_threshold(self):
        f = _FakeReplica("a")
        reg = _registry(f, fail_threshold=2)
        try:
            assert reg.is_routable("a")
        finally:
            f.stop()  # replica gone: polls now fail
        reg.poll_now()
        assert reg.is_routable("a")  # one failure: benefit of the doubt
        reg.poll_now()
        assert not reg.is_routable("a")
        assert reg.metrics.poll_errors.value == 2

    def test_mark_failed_is_immediate_readmit_needs_hysteresis(self):
        f = _FakeReplica("a")
        reg = _registry(f)  # readmit_threshold default 2
        try:
            reg.mark_failed("a")
            assert not reg.is_routable("a")
            assert reg.pick() is None
            reg.poll_now()  # first good poll: still out (hysteresis)
            assert not reg.is_routable("a")
            reg.poll_now()  # second CONSECUTIVE good poll re-admits
            assert reg.is_routable("a")
        finally:
            f.stop()

    def test_flapping_replica_stays_out_of_rotation(self):
        """Satellite regression (ISSUE 18): a replica that answers only
        every other poll must NOT oscillate in and out of rotation —
        before re-admission hysteresis, each good poll re-admitted it
        for a full poll interval and each bad one evicted it again."""
        f = _FakeReplica("a")
        reg = _registry(f, fail_threshold=1, readmit_threshold=2)
        good = dict(f.stats)
        try:
            assert reg.is_routable("a")
            for _ in range(4):     # flap: fail, ok, fail, ok, ...
                f.stats.clear()    # garbage payload = failed poll
                reg.poll_now()
                assert not reg.is_routable("a")
                f.stats.update(good)
                reg.poll_now()     # ONE good poll must not re-admit
                assert not reg.is_routable("a")
            # Steady recovery: the second consecutive good poll readmits.
            reg.poll_now()
            assert reg.is_routable("a")
        finally:
            f.stop()

    def test_canary_weighted_pick_is_deterministic(self):
        fakes = [_FakeReplica("a"), _FakeReplica("b"), _FakeReplica("c")]
        reg = _registry(*fakes)
        try:
            reg.set_canary("c", 0.25)
            picks = [reg.pick().endpoint.rid for _ in range(40)]
            # Credit accumulator: exactly weight * picks go canary-ward.
            assert picks.count("c") == 10
            # Incumbents split the rest; nobody is starved.
            assert picks.count("a") > 0 and picks.count("b") > 0
            reg.clear_canary()
            picks = [reg.pick().endpoint.rid for _ in range(9)]
            assert picks.count("c") == 3  # plain JSQ round-robin again
        finally:
            for f in fakes:
                f.stop()

    def test_canary_alone_in_rotation_still_picked(self):
        """Availability beats the traffic split: a canary that is the
        only routable replica serves everything rather than nothing."""
        fakes = [_FakeReplica("a"), _FakeReplica("b")]
        reg = _registry(*fakes)
        try:
            reg.set_canary("b", 0.1)
            fakes[0].stats["engine_state"] = "failed"
            reg.poll_now()
            picks = [reg.pick().endpoint.rid for _ in range(5)]
            assert picks == ["b"] * 5
        finally:
            for f in fakes:
                f.stop()

    def test_config_generation_tracked_from_stats(self):
        f = _FakeReplica("a")
        reg = _registry(f)
        try:
            assert reg.statuses()[0].config_gen == 0  # absent -> 0
            f.stats["config_generation"] = 3
            reg.poll_now()
            st = reg.statuses()[0]
            assert st.config_gen == 3
            assert st.as_dict()["config_generation"] == 3
        finally:
            f.stop()


# ---------------------------------------------------------------------------
# router proxy: failover semantics over fakes
# ---------------------------------------------------------------------------


@pytest.fixture
def front():
    """(router base url, fakes dict, registry, router) over two fake
    replicas, polls driven MANUALLY (no thread) for determinism."""
    fakes = {"a": _FakeReplica("a"), "b": _FakeReplica("b")}
    reg = _registry(*fakes.values())
    rt = RouterServer(reg, port=0, max_attempts=3, retry_backoff=0.01,
                      proxy_timeout=2.0, own_registry_thread=False)
    rt.start()
    host, port = rt.address
    yield f"http://{host}:{port}", fakes, reg, rt
    rt.stop()
    for f in fakes.values():
        f.stop()


class TestRouterProxy:
    def test_proxies_and_tags_replica(self, front):
        base, fakes, reg, rt = front
        code, resp, hdrs = _post(base, {"tokens": [1], "max_new_tokens": 2})
        assert code == 200 and resp["tokens"] == [1, 2, 3]
        assert hdrs["X-Router-Replica"] in ("a", "b")
        assert hdrs["X-Router-Attempts"] == "1"
        assert reg.metrics.requests.value == 1

    def test_trace_id_propagates_and_echoes(self, front):
        base, fakes, reg, rt = front
        code, resp, hdrs = _post(base, {"tokens": [1]},
                                 headers=[("X-Trace-Id", "tid-router-1")])
        assert code == 200
        assert hdrs["X-Trace-Id"] == "tid-router-1"
        served = hdrs["X-Router-Replica"]
        assert fakes[served].seen_trace_ids == ["tid-router-1"]

    def test_connection_drop_fails_over_zero_client_errors(self, front):
        base, fakes, reg, rt = front
        fakes["a"].mode = "drop"
        for _ in range(4):  # JSQ ties rotate: both replicas get tried
            code, resp, hdrs = _post(base, {"tokens": [1]})
            assert code == 200 and resp["served_by"] == "b"
        assert reg.metrics.retries.value >= 1
        assert reg.metrics.failovers.value >= 1
        assert reg.metrics.requests_failed.value == 0
        # The drop ALSO evicted a: until a poll clears it, b is alone.
        assert not reg.is_routable("a")

    def test_proxy_timeout_fails_over(self, front):
        base, fakes, reg, rt = front
        fakes["a"].mode = "hang"
        fakes["a"].hang_s = 30.0  # >> proxy_timeout=2.0
        t0 = time.monotonic()
        code, resp, hdrs = _post(base, {"tokens": [1]}, timeout=30)
        assert code == 200 and resp["served_by"] == "b"
        assert time.monotonic() - t0 < 10.0
        assert not reg.is_routable("a")

    def test_all_replicas_dead_typed_503_with_retry_after(self, front):
        base, fakes, reg, rt = front
        fakes["a"].mode = fakes["b"].mode = "drop"
        code, resp, hdrs = _post(base, {"tokens": [1]})
        assert code == 503 and resp["type"] == "no_replicas"
        assert "Retry-After" in hdrs
        assert resp["attempts"] == 2
        assert reg.metrics.requests_failed.value == 1

    def test_typed_503_from_replicas_is_relayed(self, front):
        base, fakes, reg, rt = front
        fakes["a"].mode = fakes["b"].mode = "503"
        code, resp, hdrs = _post(base, {"tokens": [1]})
        assert code == 503 and resp["type"] == "draining"
        assert "Retry-After" in hdrs
        # Both were TRIED before giving up (retry-elsewhere-first).
        assert fakes["a"].generate_hits + fakes["b"].generate_hits >= 2

    def test_429_retried_elsewhere_then_relayed(self, front):
        base, fakes, reg, rt = front
        fakes["a"].mode = "429"
        code, resp, hdrs = _post(base, {"tokens": [1]})
        assert code == 200 and resp["served_by"] == "b"
        fakes["b"].mode = "429"
        code, resp, hdrs = _post(base, {"tokens": [1]})
        assert code == 429 and resp["type"] == "queue_full"

    def test_empty_rotation_healthz_503(self):
        reg = ReplicaRegistry(poll_interval=0.05)
        rt = RouterServer(reg, port=0, own_registry_thread=False).start()
        try:
            host, port = rt.address
            try:
                urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert e.headers.get("Retry-After") is not None
                assert json.loads(e.read())["replicas_in_rotation"] == 0
        finally:
            rt.stop()

    def test_stats_and_metrics_endpoints(self, front):
        base, fakes, reg, rt = front
        _post(base, {"tokens": [1]})
        with urllib.request.urlopen(base + "/stats", timeout=5) as r:
            s = json.loads(r.read())
        assert s["policy"] == "join-shortest-queue"
        assert sorted(s["in_rotation"]) == ["a", "b"]
        assert s["replicas"]["a"]["engine_state"] == "healthy"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "# TYPE router_requests_total counter" in text
        assert "router_replicas_in_rotation" in text


class TestResumeFailover:
    """Resume-aware failover (ISSUE 9): the router re-dispatches a
    failed replica's partially decoded request WITH its resume state —
    prompt + emitted tokens, reduced decode budget, REMAINING deadline
    — and prepends the carried tokens to the final response."""

    def _front(self, a_kw=None, b_kw=None, **rt_kw):
        # a is the JSQ choice (empty queue); b is the failover target.
        fakes = {"a": _FakeReplica("a", queue_depth=0),
                 "b": _FakeReplica("b", queue_depth=5)}
        reg = _registry(*fakes.values())
        rt_kw.setdefault("max_attempts", 3)
        rt_kw.setdefault("retry_backoff", 0.01)
        rt_kw.setdefault("proxy_timeout", 2.0)
        rt = RouterServer(reg, port=0, own_registry_thread=False,
                          **rt_kw).start()
        host, port = rt.address
        return f"http://{host}:{port}", fakes, reg, rt

    def _teardown(self, fakes, rt):
        rt.stop()
        for f in fakes.values():
            f.stop()

    def test_503_descriptor_redispatches_with_resume_state(self):
        base, fakes, reg, rt = self._front()
        try:
            fakes["a"].mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 8],
                                      "deadline_remaining_ms": 5000.0}
            fakes["b"].reply_tokens = [9, 11]
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 4,
                       "timeout_ms": 60000})
            assert code == 200
            # carried + continuation, one seamless result
            assert resp["tokens"] == [7, 8, 9, 11]
            assert resp["resumed"] is True
            assert resp["resume_carried_tokens"] == 2
            assert hdrs["X-Router-Replica"] == "b"
            # b received the RESUME dispatch: frontier prompt, reduced
            # budget, remaining (not fresh) deadline
            body = fakes["b"].seen_bodies[-1]
            assert body["tokens"] == [1, 2, 7, 8]
            assert body["max_new_tokens"] == 2
            # the REMAINING budget, aged by the router's own dwell
            # time (backoff + bookkeeping) — never a fresh 60000
            assert 3000.0 < body["timeout_ms"] <= 5000.0
            m = reg.metrics
            assert m.resume_failovers.value == 1
            assert m.failovers.value == 1
        finally:
            self._teardown(fakes, rt)

    def test_deadline_expired_mid_failover_maps_to_504(self):
        """SATELLITE: the resumed budget is what is LEFT — a
        descriptor whose deadline already lapsed resolves as the
        existing typed 504, without burning another replica."""
        base, fakes, reg, rt = self._front()
        try:
            fakes["a"].mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 8],
                                      "deadline_remaining_ms": 0.0}
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 4,
                       "timeout_ms": 60000})
            assert code == 504
            assert resp["type"] == "deadline_exceeded"
            assert resp["tokens_so_far"] == [7, 8]
            assert fakes["b"].generate_hits == 0  # never dispatched
        finally:
            self._teardown(fakes, rt)

    def test_connection_drop_resumes_via_journal_lookup(self):
        """The SIGKILL signature: a dead connection yields no
        descriptor, so the router consults resume_lookup (the
        supervisor's post-mortem journal reader) and resumes from
        whatever the dead replica journaled."""
        looked_up = []

        def lookup(rid, trace_id):
            looked_up.append((rid, trace_id))
            if rid == "a":
                return {"emitted_tokens": [21, 22, 23],
                        "deadline_remaining_ms": 8000.0}
            return None

        base, fakes, reg, rt = self._front(resume_lookup=lookup)
        try:
            fakes["a"].mode = "drop"
            fakes["b"].reply_tokens = [30]
            code, resp, hdrs = _post(
                base, {"tokens": [5, 6], "max_new_tokens": 6},
                headers=[("X-Trace-Id", "tid-sigkill")])
            assert code == 200
            assert resp["tokens"] == [21, 22, 23, 30]
            assert resp["resumed"] is True
            assert looked_up == [("a", "tid-sigkill")]
            body = fakes["b"].seen_bodies[-1]
            assert body["tokens"] == [5, 6, 21, 22, 23]
            assert body["max_new_tokens"] == 3
            assert 6000.0 < body["timeout_ms"] <= 8000.0  # aged, not fresh
            assert not reg.is_routable("a")  # still evicted on the spot
            assert reg.metrics.resume_failovers.value == 1
        finally:
            self._teardown(fakes, rt)

    def test_drop_without_descriptor_reexecutes_from_scratch(self):
        """No journal, no descriptor: the pre-journal contract holds —
        plain retry of the ORIGINAL request elsewhere."""
        base, fakes, reg, rt = self._front()
        try:
            fakes["a"].mode = "drop"
            code, resp, hdrs = _post(
                base, {"tokens": [5, 6], "max_new_tokens": 6})
            assert code == 200
            assert resp["tokens"] == [1, 2, 3]
            assert "resumed" not in resp
            body = fakes["b"].seen_bodies[-1]
            assert body["tokens"] == [5, 6]
            assert body["max_new_tokens"] == 6
            assert reg.metrics.resume_failovers.value == 0
        finally:
            self._teardown(fakes, rt)

    def test_carry_exhausting_budget_completes_without_redispatch(self):
        """A descriptor whose emitted tokens already spend the whole
        decode budget (the replica died after its last token, before
        answering): the router finishes the request from the carry —
        re-dispatching would send max_new_tokens=0 and bounce as a
        400."""
        base, fakes, reg, rt = self._front()
        try:
            fakes["a"].mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 8, 9],
                                      "deadline_remaining_ms": 5000.0}
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 3,
                       "timeout_ms": 60000})
            assert code == 200
            assert resp["tokens"] == [7, 8, 9]
            assert resp["finish_reason"] == "length"
            assert resp["resumed"] is True
            assert fakes["b"].generate_hits == 0  # nothing re-dispatched
            assert reg.metrics.resume_failovers.value == 1
        finally:
            self._teardown(fakes, rt)

    def test_carry_ending_in_eos_completes_without_redispatch(self):
        """A carried tail ending in eos_id is a FINISHED generation —
        continuing it elsewhere would decode past EOS, emitting tokens
        an uninterrupted run never would."""
        base, fakes, reg, rt = self._front()
        try:
            fakes["a"].mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 42],
                                      "deadline_remaining_ms": 5000.0}
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 9,
                       "eos_id": 42, "timeout_ms": 60000})
            assert code == 200
            assert resp["tokens"] == [7, 42]
            assert resp["finish_reason"] == "eos"
            assert resp["resumed"] is True
            assert fakes["b"].generate_hits == 0
        finally:
            self._teardown(fakes, rt)

    def test_exhausted_attempts_relay_carries_full_resume_state(self):
        """Every replica failed typed: the relayed 503's descriptor is
        rewritten to the FULL accumulated frontier, so an upstream
        caller can itself resume from the true position."""
        base, fakes, reg, rt = self._front(max_attempts=2)
        try:
            for f in fakes.values():
                f.mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 8],
                                      "deadline_remaining_ms": 9000.0}
            fakes["b"].resume_desc = {"emitted_tokens": [9],
                                      "deadline_remaining_ms": 7000.0}
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 6,
                       "timeout_ms": 60000})
            assert code == 503 and resp["type"] == "engine_failed"
            assert resp["resume"]["emitted_tokens"] == [7, 8, 9]
            # b's dispatch already carried a's tokens
            body = fakes["b"].seen_bodies[-1]
            assert body["tokens"] == [1, 2, 7, 8]
            assert body["max_new_tokens"] == 4
        finally:
            self._teardown(fakes, rt)


# ---------------------------------------------------------------------------
# distributed tracing at the router: span parentage, force-sampling,
# header validation at ROUTER ingress, and the /trace/<id> autopsy
# ---------------------------------------------------------------------------


@pytest.mark.tracing
class TestRouterSpans:
    def _front(self, tmp_path, span_dir=True, **rt_kw):
        from horovod_tpu.obs import tracing as TR

        assert TR.spans() is None
        rec = TR.start_spans(
            str(tmp_path / "router.spans.jsonl"), proc="router",
            role="router",
            sampling=TR.SpanSampling(latency_threshold_s=600.0))
        fakes = {"a": _FakeReplica("a", queue_depth=0),
                 "b": _FakeReplica("b", queue_depth=5)}
        reg = _registry(*fakes.values())
        rt_kw.setdefault("max_attempts", 3)
        rt_kw.setdefault("retry_backoff", 0.01)
        rt_kw.setdefault("proxy_timeout", 2.0)
        if span_dir:
            rt_kw.setdefault("span_dir", str(tmp_path))
        rt = RouterServer(reg, port=0, own_registry_thread=False,
                          **rt_kw).start()
        host, port = rt.address
        return f"http://{host}:{port}", fakes, reg, rt, rec

    def _teardown(self, fakes, rt):
        from horovod_tpu.obs import tracing as TR

        rt.stop()
        for f in fakes.values():
            f.stop()
        TR.stop_spans()

    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_failover_builds_one_tree_with_attempt_parentage(
            self, tmp_path):
        """A 503-resume failover: the router's stream carries root +
        two attempt spans, each dispatch carries ITS attempt span id in
        X-Parent-Span, the continuation is force-sampled
        (X-Trace-Sampled), and GET /trace/<id> assembles the tree with
        the resume edge and carried-token accounting."""
        base, fakes, reg, rt, rec = self._front(tmp_path)
        try:
            fakes["a"].mode = "503resume"
            fakes["a"].resume_desc = {"emitted_tokens": [7, 8],
                                      "deadline_remaining_ms": 5000.0,
                                      "span_id": "deadbeefdeadbeef"}
            fakes["b"].reply_tokens = [9, 11]
            code, resp, hdrs = _post(
                base, {"tokens": [1, 2], "max_new_tokens": 4,
                       "timeout_ms": 60000})
            assert code == 200 and resp["resumed"] is True
            tid = hdrs["X-Trace-Id"]
            # each replica saw a DIFFERENT parent (its own attempt span)
            pa, pb = (fakes["a"].seen_parent_spans[-1],
                      fakes["b"].seen_parent_spans[-1])
            assert pa and pb and pa != pb
            # first attempt: nothing interesting yet — not forced;
            # the failover continuation IS forced end to end
            assert fakes["a"].seen_sampled[-1] is None
            assert fakes["b"].seen_sampled[-1] == "1"

            code, autopsy = self._get(f"{base}/trace/{tid}")
            assert code == 200
            assert autopsy["resumed"] is True
            assert autopsy["carried_tokens"] == 2
            assert autopsy["retries"] == 1
            root = autopsy["tree"][0]
            assert root["name"] == "router /generate"
            att = {c["name"]: c for c in root["children"]}
            assert set(att) == {"attempt 1 -> a", "attempt 2 -> b"}
            assert att["attempt 1 -> a"]["span_id"] == pa
            assert att["attempt 2 -> b"]["span_id"] == pb
            assert att["attempt 1 -> a"]["status"] == "http:503"
            assert att["attempt 2 -> b"]["status"] == "http:200"
            resume_ev = [e for e in autopsy["events"]
                         if e["type"] == "resume"][0]
            assert resume_ev["attrs"]["carried"] == 2
            # the descriptor's span id links the dead attempt in
            assert resume_ev["attrs"]["resumed_from_span"] \
                == "deadbeefdeadbeef"
            assert root["attrs"]["attempts"] == 2
            assert root["attrs"]["resumed"] is True
            assert root["status"] == "http:200"
        finally:
            self._teardown(fakes, rt)

    def test_router_ingress_parent_span_validation(self, tmp_path):
        """ROUTER-ingress twins of the replica-ingress edge cases
        (tests/test_tracing.py): a client X-Parent-Span nests the
        router root — but only alongside a VALID X-Trace-Id; spoofed /
        malformed / oversized parents are dropped."""
        base, fakes, reg, rt, rec = self._front(tmp_path)
        try:
            cases = [
                ({"X-Trace-Id": "up-1", "X-Parent-Span": "c" * 16},
                 "up-1", "c" * 16),       # valid: honored
                ({"X-Parent-Span": "d" * 16},
                 None, None),             # spoofed on a fresh trace
                ({"X-Trace-Id": "up-2", "X-Parent-Span": "x" * 65},
                 "up-2", None),           # oversized
                ({"X-Trace-Id": "up-3", "X-Parent-Span": "sp ace"},
                 "up-3", None),           # malformed
                ({"X-Trace-Id": "bad id!", "X-Parent-Span": "e" * 16},
                 None, None),             # invalid trace id => both out
            ]
            for headers, want_tid, want_parent in cases:
                code, resp, _ = _post(
                    base, {"tokens": [1], "max_new_tokens": 2},
                    headers=headers)
                assert code == 200
                tid = resp.get("trace_id") or \
                    fakes["a"].seen_trace_ids[-1]
                if want_tid is not None:
                    assert tid == want_tid
                with open(rec.path) as f:
                    roots = [json.loads(l) for l in f
                             if '"router /generate"' in l]
                root = [r for r in roots if r["trace"] == tid][-1]
                assert root.get("parent") == want_parent, headers
        finally:
            self._teardown(fakes, rt)

    def test_client_force_sample_rides_through_to_the_replica(
            self, tmp_path):
        """X-Trace-Sampled from the CLIENT (with a valid trace id — the
        same trust gate as X-Parent-Span) must reach the replica on the
        FIRST attempt: it is the documented way to capture one
        request's full tick detail through the front tier."""
        base, fakes, reg, rt, rec = self._front(tmp_path)
        try:
            _post(base, {"tokens": [1], "max_new_tokens": 2},
                  headers={"X-Trace-Id": "force-1",
                           "X-Trace-Sampled": "1"})
            assert fakes["a"].seen_sampled[-1] == "1"
            # the gate: no (valid) trace id => not trusted
            _post(base, {"tokens": [1], "max_new_tokens": 2},
                  headers={"X-Trace-Sampled": "1"})
            assert fakes["a"].seen_sampled[-1] is None
        finally:
            self._teardown(fakes, rt)

    def test_client_parent_forwarded_without_router_recorder(self):
        """A replicas-only span deployment (no recorder in the router
        process): the client's validated X-Parent-Span must still be
        FORWARDED so the replica's span joins the upstream tree —
        dropped silently only when invalid/untrusted."""
        from horovod_tpu.obs import tracing as TR

        assert TR.spans() is None  # no router recorder in this test
        fakes = {"a": _FakeReplica("a")}
        reg = _registry(*fakes.values())
        rt = RouterServer(reg, port=0, own_registry_thread=False,
                          max_attempts=2, proxy_timeout=2.0).start()
        try:
            host, port = rt.address
            base = f"http://{host}:{port}"
            _post(base, {"tokens": [1], "max_new_tokens": 2},
                  headers={"X-Trace-Id": "up-fwd",
                           "X-Parent-Span": "f" * 16})
            assert fakes["a"].seen_parent_spans[-1] == "f" * 16
            _post(base, {"tokens": [1], "max_new_tokens": 2},
                  headers={"X-Parent-Span": "f" * 16})  # no trace id
            assert fakes["a"].seen_parent_spans[-1] is None
        finally:
            rt.stop()
            fakes["a"].stop()

    def test_trace_endpoint_error_paths(self, tmp_path):
        base, fakes, reg, rt, rec = self._front(tmp_path)
        try:
            code, resp = self._get(f"{base}/trace/not!valid!")
            assert code == 400 and resp["type"] == "bad_trace_id"
            code, resp = self._get(f"{base}/trace/{'0' * 16}")
            assert code == 404 and resp["type"] == "unknown_trace"
            # a broken STORE must not masquerade as a missing trace
            rt.span_dir = str(tmp_path / "moved_or_mistyped")
            code, resp = self._get(f"{base}/trace/{'0' * 16}")
            assert code == 500 and resp["type"] == "span_store_error"
        finally:
            self._teardown(fakes, rt)

    def test_trace_endpoint_without_span_dir_is_typed_503(
            self, tmp_path):
        base, fakes, reg, rt, rec = self._front(tmp_path,
                                                span_dir=False)
        try:
            code, resp = self._get(f"{base}/trace/{'0' * 16}")
            assert code == 503 and resp["type"] == "no_span_store"
        finally:
            self._teardown(fakes, rt)


# ---------------------------------------------------------------------------
# the /stats routing contract + Retry-After on a REAL engine
# ---------------------------------------------------------------------------


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


@pytest.mark.serving
class TestStatsContract:
    def test_contract_keys_always_present_and_typed(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=16))
        snap = engine.stats()  # BEFORE any tick: the cold-start shape
        assert isinstance(snap["queue_depth"], int)
        assert isinstance(snap["occupancy"], float)
        assert isinstance(snap["engine_state"], str)
        assert isinstance(snap["heartbeat_age_s"], float)
        assert snap["heartbeat_age_s"] == -1.0  # no tick yet, not null
        assert snap["engine_state"] == "healthy"

        fut = engine.submit([1, 2, 3], max_new_tokens=3)
        while not fut.done():
            engine.step()
        snap = engine.stats()
        assert snap["heartbeat_age_s"] >= 0.0
        assert isinstance(snap["occupancy"], float)
        assert isinstance(snap["queue_depth"], int)

    def test_registry_polls_a_real_server(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=16))
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            reg = ReplicaRegistry(poll_interval=0.05)
            reg.add(ReplicaEndpoint("real", host, port))
            reg.poll_now()
            assert reg.is_routable("real")
            engine.begin_drain()
            reg.poll_now()
            assert not reg.is_routable("real")  # draining leaves rotation

    def test_draining_503_carries_retry_after(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=16))
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            engine.begin_drain()
            code, resp, hdrs = _post(
                f"http://{host}:{port}",
                {"tokens": [1, 2], "max_new_tokens": 2})
            assert code == 503 and resp["type"] == "draining"
            assert hdrs.get("Retry-After") == "1"
            try:
                urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=5)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert e.headers.get("Retry-After") is not None
                assert isinstance(
                    json.loads(e.read())["heartbeat_age_s"], float)


# ---------------------------------------------------------------------------
# supervisor unit: crash-loop backoff without JAX subprocess weight
# ---------------------------------------------------------------------------


class TestSupervisorBackoff:
    def test_crash_loop_respawns_with_backoff(self):
        import sys

        def cmd(slot, port):
            return [sys.executable, "-c", "import sys; sys.exit(3)"]

        reg = ReplicaRegistry(poll_interval=10.0)  # polls irrelevant
        sup = ReplicaSupervisor(cmd, 1, registry=reg,
                                backoff_initial=0.15, backoff_max=0.6,
                                backoff_reset_after=999.0,
                                monitor_interval=0.02)
        sup.start()
        try:
            deadline = time.monotonic() + 8.0
            while (reg.metrics.replica_restarts.value < 3
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            restarts = reg.metrics.replica_restarts.value
            assert restarts >= 3, "supervisor stopped respawning"
            h = sup.handle(0)
            assert h.gen >= 3 and h.rid == f"r0g{h.gen}"
        finally:
            sup.stop(drain=False)
        # Exponential backoff rate-limited the loop: in ~a second of
        # 0.15 * 2^n delays there cannot have been tens of respawns.
        assert reg.metrics.replica_restarts.value < 15

    def test_spec_command_and_fault_parsing(self):
        spec = ReplicaSpec(seed=7, slots=3, warm=(8, 16),
                           faults=("decode_tick:hang:5:2.5",))
        cmd = spec.command(1234)
        assert "--port" in cmd and "1234" in cmd
        assert cmd.count("--warm") == 2 and "--fault" in cmd
        f = parse_fault("decode_tick:hang:5:2.5")
        assert (f.site, f.kind, f.skip, f.delay) == \
            ("decode_tick", "hang", 5, 2.5)
        with pytest.raises(Exception):
            parse_fault("nonsense")


# ---------------------------------------------------------------------------
# chaos: real replica processes, real kills
# ---------------------------------------------------------------------------


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _burst(base, prompts, steps, kill_after=None, timeout=60):
    """Fire one concurrent request per prompt; optionally invoke
    ``kill_after()`` once half of them are in flight.  Returns
    ``{i: (code, payload)}`` — an entry for EVERY request (a transport
    error to the ROUTER itself would be a dropped request and fails
    the caller's assertions by absence)."""
    results = {}
    started = threading.Semaphore(0)

    def client(i):
        started.release()
        try:
            code, resp, _ = _post(base, {"tokens": prompts[i],
                                         "max_new_tokens": steps},
                                  timeout=timeout)
            results[i] = (code, resp)
        except Exception as e:  # transport failure = a DROP
            results[i] = (None, repr(e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    if kill_after is not None:
        for _ in range(len(prompts) // 2):
            started.acquire()
        kill_after()
    for t in threads:
        t.join()
    return results


@pytest.mark.chaos
@pytest.mark.slow
class TestFrontTierChaos:
    """The acceptance invariant (ISSUE 8): with 3 replicas under
    concurrent load, killing one mid-decode drops ZERO requests; the
    router evicts it within a poll, the supervisor respawns it, and it
    rejoins rotation serving oracle-identical greedy output."""

    N_REPLICAS = 3

    def _front_tier(self, spec_or_cmd, **sup_kw):
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=3.0)
        sup_kw.setdefault("unhealthy_grace", 1.5)
        sup_kw.setdefault("shutdown_grace", 2.0)
        sup_kw.setdefault("backoff_initial", 0.1)
        sup = ReplicaSupervisor(spec_or_cmd, self.N_REPLICAS,
                                registry=reg, **sup_kw)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=8.0)
        return reg, sup, rt

    def test_sigkill_mid_decode_resumes_on_survivor(self, model):
        """ACCEPTANCE (ISSUE 9): SIGKILL a replica mid-decode under
        concurrent load, with request journaling armed.  The router
        reads the dead replica's journal post-mortem and CONTINUES its
        partially decoded requests on the survivor — every request
        resolves 200 with output byte-identical to the no-fault greedy
        oracle, at least one of them via a genuine resume (carried
        tokens > 0), and the wasted work is one re-prefill, not a
        re-execution."""
        params, cfg = model
        spec = ReplicaSpec(seed=0, slots=4, warm=(8, 30),
                           tick_timeout=30.0, drain_timeout=3.0,
                           request_timeout=90.0)
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        journal_dir = tempfile.mkdtemp(prefix="router_journal_")
        sup = ReplicaSupervisor(spec, 2, registry=reg,
                                unhealthy_grace=1.5, shutdown_grace=2.0,
                                backoff_initial=0.1,
                                journal_dir=journal_dir)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup)
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=240), "replicas never ready"
            host, port = rt.address
            base = f"http://{host}:{port}"
            steps = 24
            rng = np.random.default_rng(3)
            prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                       for i in range(6)]

            def kill_busy_replica():
                """SIGKILL a replica whose JOURNAL shows a request
                genuinely mid-decode (enough emitted to prove a real
                carry, enough remaining that it cannot retire between
                this check and the kill) — /stats counters are
                cumulative and could pick a victim whose work just
                finished, leaving nothing to resume."""
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    for h in sup.replicas():
                        try:
                            live = serving.RequestJournal.read_live(
                                sup._journal_paths[h.rid])
                        except Exception:
                            continue
                        if any(5 <= len(d["emitted_tokens"]) <= steps - 8
                               for d in live.values()):
                            os.kill(h.pid, signal.SIGKILL)
                            return
                    time.sleep(0.02)
                raise AssertionError("no replica ever got mid-decode")

            results = _burst(base, prompts, steps, timeout=120,
                             kill_after=kill_busy_replica)

            assert len(results) == len(prompts)
            drops = [i for i, (c, _) in results.items() if c is None]
            assert not drops, f"transport-dropped requests: {results}"
            resumed_carried = 0
            for i, (code, resp) in results.items():
                assert code == 200, f"req {i}: {code} {resp}"
                # byte-identical to the no-fault oracle, THROUGH the
                # kill and the resume
                assert resp["tokens"] == _ref_greedy(
                    params, cfg, prompts[i], steps), f"req {i}"
                if resp.get("resumed"):
                    resumed_carried += resp["resume_carried_tokens"]
            # at least one request truly CONTINUED mid-decode (the
            # victim had >= 8 tokens generated when killed)
            assert resumed_carried >= 1, \
                f"no request resumed: {results}"
            assert reg.metrics.resume_failovers.value >= 1
        finally:
            rt.stop()
            sup.stop(drain=False)

    def test_sigkill_replica_zero_dropped_requests(self, model):
        params, cfg = model
        spec = ReplicaSpec(seed=0, slots=3, warm=(8,),
                           tick_timeout=30.0, drain_timeout=3.0)
        reg, sup, rt = self._front_tier(spec)
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=180), "replicas never ready"
            host, port = rt.address
            base = f"http://{host}:{port}"

            rng = np.random.default_rng(0)
            steps = 8
            prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                       for i in range(18)]
            victim = sup.handle(1)

            results = _burst(
                base, prompts, steps,
                kill_after=lambda: os.kill(victim.pid, signal.SIGKILL))

            # 1) ZERO drops: every request resolved through the router
            #    with tokens (typed errors allowed by the invariant,
            #    but with 2 healthy survivors none should occur).
            assert len(results) == len(prompts)
            drops = [i for i, (c, _) in results.items() if c is None]
            assert not drops, f"transport-dropped requests: {results}"
            for i, (code, resp) in results.items():
                assert code == 200, f"req {i}: {code} {resp}"
                # 2) oracle-identity THROUGH failover: greedy tokens
                #    equal per-request greedy_decode, whichever replica
                #    (including a retry target) served them.
                assert resp["tokens"] == _ref_greedy(
                    params, cfg, prompts[i], steps), f"req {i}"

            # 3) the dead replica left rotation (within ~a poll; the
            #    burst's own mark_failed usually beat the poll to it).
            deadline = time.monotonic() + 5.0
            while (victim.rid in {s.endpoint.rid
                                  for s in reg.in_rotation()}
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert victim.rid not in {
                s.endpoint.rid for s in reg.in_rotation()}

            # 4) the supervisor respawns it and it REJOINS rotation …
            deadline = time.monotonic() + 120.0
            while (len(reg.in_rotation()) < self.N_REPLICAS
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            assert len(reg.in_rotation()) == self.N_REPLICAS
            fresh = sup.handle(1)
            assert fresh.gen == victim.gen + 1
            assert reg.metrics.replica_restarts.value >= 1

            # 5) … serving oracle-identical output (probe repeatedly:
            #    JSQ spreads probes over the pool, so the respawned
            #    replica answers at least one).
            seen = set()
            for k in range(12):
                code, resp, hdrs = _post(
                    base, {"tokens": prompts[0], "max_new_tokens": steps})
                assert code == 200
                assert resp["tokens"] == _ref_greedy(
                    params, cfg, prompts[0], steps)
                seen.add(hdrs["X-Router-Replica"])
                if fresh.rid in seen:
                    break
            assert fresh.rid in seen, \
                f"respawned replica never served: {seen}"
        finally:
            rt.stop()
            sup.stop(drain=False)

    def test_hang_replica_zero_dropped_requests(self, model):
        """FaultInjector-hang: slot 0's engine wedges mid-decode (hang
        with the watchdog DISABLED — the worst case: the process is
        alive, HTTP answers, the engine never ticks again).  In-flight
        proxied requests ride the proxy timeout onto a survivor; the
        stale heartbeat evicts it; the supervisor drains (SIGTERM →
        SIGKILL escalation) and respawns it CLEAN."""
        params, cfg = model
        hang = ReplicaSpec(seed=0, slots=3, warm=(8,), tick_timeout=0.0,
                           drain_timeout=1.0,
                           faults=("decode_tick:hang:8:600",))
        clean = ReplicaSpec(seed=0, slots=3, warm=(8,),
                            tick_timeout=30.0, drain_timeout=3.0)
        first_spawn = set()

        def cmd(slot, port):
            # Only slot 0's FIRST generation carries the fault: the
            # respawn must come back clean.
            spec = hang if slot == 0 and slot not in first_spawn \
                else clean
            first_spawn.add(slot)
            return spec.command(port)

        reg, sup, rt = self._front_tier(cmd)
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=180), "replicas never ready"
            victim = sup.handle(0)
            host, port = rt.address
            base = f"http://{host}:{port}"

            rng = np.random.default_rng(1)
            steps = 6
            prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                       for i in range(15)]
            # No kill callback: the fault fires by itself once slot 0
            # has dispatched 12 decode ticks (warmup spent ~a handful).
            results = _burst(base, prompts, steps, timeout=90)

            assert len(results) == len(prompts)
            drops = [i for i, (c, _) in results.items() if c is None]
            assert not drops, f"transport-dropped requests: {results}"
            resolved_with_tokens = 0
            for i, (code, resp) in results.items():
                assert code in (200, 429, 503, 504), \
                    f"req {i}: {code} {resp}"
                if code == 200:
                    resolved_with_tokens += 1
                    assert resp["tokens"] == _ref_greedy(
                        params, cfg, prompts[i], steps), f"req {i}"
                else:
                    assert "type" in resp, f"untyped error: {resp}"
            # The survivors carried the load: the overwhelming majority
            # completed with tokens despite a wedged replica.
            assert resolved_with_tokens >= len(prompts) - 3

            # Eviction (stale heartbeat or proxy timeout), then the
            # supervisor's drain → respawn brings back a clean gen 1.
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                h = sup.handle(0)
                if (h.gen >= victim.gen + 1
                        and len(reg.in_rotation()) == self.N_REPLICAS):
                    break
                time.sleep(0.2)
            assert sup.handle(0).gen >= victim.gen + 1, \
                "wedged replica never respawned"
            assert len(reg.in_rotation()) == self.N_REPLICAS
            code, resp, _ = _post(base, {"tokens": prompts[0],
                                         "max_new_tokens": steps})
            assert code == 200 and resp["tokens"] == _ref_greedy(
                params, cfg, prompts[0], steps)
        finally:
            rt.stop()
            sup.stop(drain=False)

    @pytest.mark.tracing
    def test_sigkill_autopsy_one_tree_and_tail_sampling(self, model):
        """ACCEPTANCE (ISSUE 12): SIGKILL a replica mid-decode under
        the router with journaling AND span streams armed.  GET
        /trace/<id> for an affected request returns ONE tree showing
        BOTH replica attempts (the dead one as an UNFINISHED span —
        the kill evidence), the failover + resume edges with
        carried-token accounting linking the continuation to the dead
        attempt's span id — while the response stays byte-identical to
        the no-fault oracle.  And a clean request under no fault is
        correctly tail-dropped: its breakdown survives on the span's
        finish record, its tick-level detail does not."""
        from horovod_tpu.obs import tracing as TR

        params, cfg = model
        span_dir = tempfile.mkdtemp(prefix="router_spans_")
        journal_dir = tempfile.mkdtemp(prefix="router_journal_")
        spec = ReplicaSpec(
            seed=0, slots=4, warm=(8, 30), tick_timeout=30.0,
            drain_timeout=3.0, request_timeout=90.0,
            # latency can't trigger retention on this slow CPU config:
            # only the failover/resume path may keep tick detail
            extra_args=("--span-latency-threshold", "600"))
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        sup = ReplicaSupervisor(spec, 2, registry=reg,
                                unhealthy_grace=1.5, shutdown_grace=2.0,
                                backoff_initial=0.1,
                                journal_dir=journal_dir,
                                span_dir=span_dir)
        assert TR.spans() is None
        TR.start_spans(os.path.join(span_dir, "router.spans.jsonl"),
                       proc="router", role="router",
                       sampling=TR.SpanSampling(
                           latency_threshold_s=600.0))
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup,
                          span_dir=span_dir)
        sup.start()
        rt.start()

        def walk(node):
            yield node
            for c in node["children"]:
                yield from walk(c)

        try:
            assert sup.wait_ready(timeout=240), "replicas never ready"
            host, port = rt.address
            base = f"http://{host}:{port}"
            steps = 24
            rng = np.random.default_rng(7)
            prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                       for i in range(6)]

            def kill_busy_replica():
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    for h in sup.replicas():
                        try:
                            live = serving.RequestJournal.read_live(
                                sup._journal_paths[h.rid])
                        except Exception:
                            continue
                        if any(5 <= len(d["emitted_tokens"]) <= steps - 8
                               for d in live.values()):
                            os.kill(h.pid, signal.SIGKILL)
                            return
                    time.sleep(0.02)
                raise AssertionError("no replica ever got mid-decode")

            results = _burst(base, prompts, steps, timeout=120,
                             kill_after=kill_busy_replica)

            assert len(results) == len(prompts)
            assert not [i for i, (c, _) in results.items() if c is None]
            resumed_tid = None
            for i, (code, resp) in results.items():
                assert code == 200, f"req {i}: {code} {resp}"
                # byte-identical to the no-fault oracle, THROUGH the
                # kill, the failover, and the resume
                assert resp["tokens"] == _ref_greedy(
                    params, cfg, prompts[i], steps), f"req {i}"
                if resp.get("resumed") and resumed_tid is None:
                    resumed_tid = resp["trace_id"]
            assert resumed_tid is not None, f"no resume: {results}"

            # --- the autopsy: ONE tree, both attempts, typed edges ---
            def get(url):
                try:
                    with urllib.request.urlopen(url, timeout=15) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            code, autopsy = get(f"{base}/trace/{resumed_tid}")
            assert code == 200
            assert autopsy["resumed"] is True
            assert autopsy["failovers"] >= 1
            assert autopsy["carried_tokens"] >= 1
            assert "router" in autopsy["processes"]
            assert len(autopsy["processes"]) >= 3  # router + 2 replicas
            assert len(autopsy["tree"]) == 1, "ONE tree, one root"
            spans = list(walk(autopsy["tree"][0]))
            gen_spans = [s for s in spans if s["name"] == "generate"]
            assert len(gen_spans) >= 2, "both replica attempts present"
            assert len({s["proc"] for s in gen_spans}) >= 2
            dead = [s for s in gen_spans if s["unfinished"]]
            done = [s for s in gen_spans if not s["unfinished"]]
            assert dead and done, (
                "the killed attempt must surface UNFINISHED and the "
                f"survivor finished: {gen_spans}")
            resume_ev = [e for e in autopsy["events"]
                         if e["type"] == "resume"
                         and e["attrs"].get("source") == "journal"][0]
            assert resume_ev["attrs"]["carried"] \
                == autopsy["carried_tokens"]
            # the journal's span id links the resume edge to the DEAD
            # attempt's span — the tree is causal, not just temporal
            assert resume_ev["attrs"]["resumed_from_span"] \
                in {s["span_id"] for s in dead}
            # the survivor's share was force-sampled end to end: its
            # tick-level detail is IN the tree despite clean latency
            survivor_ticks = [s for s in spans if s["name"] == "tick"
                              and s["proc"] == done[0]["proc"]]
            assert survivor_ticks, "forced retention on the resume leg"

            # --- tail sampling: a clean request keeps only breakdown --
            code, resp, _ = _post(base, {"tokens": prompts[0],
                                         "max_new_tokens": 4})
            assert code == 200
            clean_tid = resp["trace_id"]
            code, clean = get(f"{base}/trace/{clean_tid}")
            assert code == 200
            cspans = [s for root in clean["tree"]
                      for s in walk(root)]
            assert not [s for s in cspans if s["name"] == "tick"], \
                "clean-load trace must be tail-dropped"
            cgen = [s for s in cspans if s["name"] == "generate"][0]
            assert cgen["attrs"]["decode_ticks"] == 3  # breakdown kept
            assert "retained" not in cgen["attrs"]
            assert clean["detail_spans_dropped"] >= 1
        finally:
            rt.stop()
            sup.stop(drain=False)
            TR.stop_spans()


@pytest.mark.chaos
@pytest.mark.slow
class TestRolloutDrainChaos:
    """SATELLITE drill (tests/test_rollout.py owns the rollout suite;
    this one lives here because it exercises the FRONT-TIER failover
    path): SIGKILL a replica at the exact moment a rollout is
    draining it.  The drain's SIGTERM already told it to finish its
    in-flight work; the SIGKILL means it cannot — so those requests
    must fail over and RESUME byte-identical on the survivor, while
    the rollout itself (tripped by an injected canary fault) rolls
    back cleanly to an all-incumbent fleet.  Slow (real replica
    subprocesses); tier-1 siblings: TestResumeFailover here and
    test_rollout.py's TestRolloutMachine fault matrix."""

    def test_sigkill_mid_rollout_drain_resumes_and_rolls_back(
            self, model):
        params, cfg = model
        steps = 24
        rng = np.random.default_rng(17)
        prompts = [[int(t) for t in rng.integers(1, 60, 2 + i % 3)]
                   for i in range(6)]
        # Oracle BEFORE the fleet exists: the XLA compile runs in a
        # pristine process, off the CPU the replicas are about to
        # saturate.
        oracle = {tuple(p): _ref_greedy(params, cfg, p, steps)
                  for p in prompts}
        spec = ReplicaSpec(seed=0, slots=4, warm=(8, 30),
                           tick_timeout=30.0, drain_timeout=5.0,
                           request_timeout=90.0)
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        journal_dir = tempfile.mkdtemp(prefix="rollout_drain_chaos_")
        sup = ReplicaSupervisor(spec, 2, registry=reg,
                                unhealthy_grace=1.5, shutdown_grace=2.0,
                                backoff_initial=0.1,
                                journal_dir=journal_dir)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup)
        # The canary fault guarantees the rollout TRIPS after the
        # rebuild, so the drill proves rollback convergence too.
        ctl = RolloutController(
            sup, canary_windows=1, window_s=0.5, ready_timeout=240.0,
            faults=serving.FaultInjector([serving.FaultSpec(
                site="rollout_canary", kind="raise")]))
        rt.rollout = ctl
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=240), "replicas never ready"
            host, port = rt.address
            base = f"http://{host}:{port}"

            def rollout_then_kill_draining():
                """Start the rollout (slot 0 drains first), then
                SIGKILL that exact process the moment the SIGTERM
                lands — its in-flight share cannot finish locally."""
                h0 = sup.handle(0)
                assert h0 is not None
                ctl.start({"max_prefills_per_tick": 4})
                deadline = time.monotonic() + 60.0
                while (h0.term_sent_at is None
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                assert h0.term_sent_at is not None, "drain never began"
                os.kill(h0.pid, signal.SIGKILL)

            results = _burst(base, prompts, steps, timeout=120,
                             kill_after=rollout_then_kill_draining)

            assert len(results) == len(prompts)
            drops = [i for i, (c, _) in results.items() if c is None]
            assert not drops, f"transport-dropped requests: {results}"
            for i, (code, resp) in results.items():
                assert code == 200, f"req {i}: {code} {resp}"
                assert resp["tokens"] == oracle[tuple(prompts[i])], \
                    f"req {i}"

            # the rollout tripped and converged back to the incumbent
            assert ctl.wait(480.0), f"rollout wedged in {ctl.state}"
            assert ctl.state == "rolled_back", ctl.state
            assert "InjectedFaultError" in ctl.trip_reason
            snap = reg.metrics.snapshot()
            assert snap["rollout_rollbacks"] == 1
            assert snap["rollout_promotions"] == 0
            time.sleep(0.5)
            gens = set()
            for st in reg.statuses():
                try:
                    with urllib.request.urlopen(
                            st.endpoint.base_url + "/stats",
                            timeout=2.0) as r:
                        gens.add(json.loads(r.read())
                                 .get("config_generation"))
                except Exception:
                    pass
            assert gens == {0}, f"fleet not all-incumbent: {gens}"
            assert sup.spec.config_gen == 0
        finally:
            rt.stop()
            sup.stop()
