"""Per-slot vectorized sampling (horovod_tpu/serving/sampling.py +
models/transformer.py:sample_token_rows).

The gold check mirrors the engine's greedy story: whatever MIX of
greedy / temperature / top-k / top-p requests shares the slot pool,
each one's sampled stream must be token-identical to per-request
``sample_decode`` at the same seed — the per-request oracle — with
ZERO decode recompilations across the whole mix (sampling parameters
are data, not structure).  The PRNG key schedule is position-based, so
the same identity must survive a restart-resume (re-prefill of
``prompt + emitted``) unchanged.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving import sampling as S
from horovod_tpu.serving.faults import FaultInjector, FaultSpec

pytestmark = pytest.mark.serving


def _cfg(**kw):
    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _oracle(params, cfg, prompt, steps, *, temperature=0.0, top_k=0,
            top_p=0.0, seed=0):
    return np.asarray(T.sample_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg,
        rng=jax.random.PRNGKey(seed), temperature=temperature,
        top_k=top_k, top_p=top_p))[0].tolist()


def _run(engine, futs, max_ticks=600):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


# ---------------------------------------------------------------------------
# kernel units
# ---------------------------------------------------------------------------


class TestSampleTokenRows:
    def _logits(self, rows=4, vocab=32, seed=0):
        return jax.random.normal(jax.random.PRNGKey(seed),
                                 (rows, vocab)).astype(jnp.float32)

    def _pick(self, logits, temp, tk, tp, seeds, positions):
        r = logits.shape[0]
        keys = jnp.asarray(np.stack([S.seed_key(s) for s in seeds]))
        return np.asarray(T.sample_token_rows(
            logits, jnp.asarray(temp, jnp.float32),
            jnp.asarray(tk, jnp.int32), jnp.asarray(tp, jnp.float32),
            keys, jnp.asarray(positions, jnp.int32),
            jnp.zeros((r,), jnp.int32)))

    def test_greedy_rows_are_argmax(self):
        lg = self._logits()
        out = self._pick(lg, [0.0] * 4, [0] * 4, [0.0] * 4,
                         [1, 2, 3, 4], [5] * 4)
        np.testing.assert_array_equal(out, np.argmax(np.asarray(lg), -1))

    def test_top_k_one_is_argmax(self):
        lg = self._logits()
        out = self._pick(lg, [2.0] * 4, [1] * 4, [0.0] * 4,
                         [7, 8, 9, 10], [3] * 4)
        np.testing.assert_array_equal(out, np.argmax(np.asarray(lg), -1))

    def test_top_p_tiny_is_argmax(self):
        # The nucleus always keeps index 0 of the sorted order — a
        # top_p below any single probability keeps ONLY the argmax.
        lg = self._logits()
        out = self._pick(lg, [1.0] * 4, [0] * 4, [1e-9] * 4,
                         [7, 8, 9, 10], [3] * 4)
        np.testing.assert_array_equal(out, np.argmax(np.asarray(lg), -1))

    def test_top_k_masks_to_top_set(self):
        lg = self._logits(rows=8, vocab=32, seed=3)
        out = self._pick(lg, [5.0] * 8, [4] * 8, [0.0] * 8,
                         list(range(8)), list(range(8)))
        top4 = np.argsort(-np.asarray(lg), axis=-1)[:, :4]
        for r in range(8):
            assert out[r] in top4[r]

    def test_deterministic_and_seed_sensitive(self):
        lg = self._logits(rows=8)
        a = self._pick(lg, [3.0] * 8, [0] * 8, [0.0] * 8,
                       list(range(8)), [2] * 8)
        b = self._pick(lg, [3.0] * 8, [0] * 8, [0.0] * 8,
                       list(range(8)), [2] * 8)
        np.testing.assert_array_equal(a, b)
        c = self._pick(lg, [3.0] * 8, [0] * 8, [0.0] * 8,
                       [s + 100 for s in range(8)], [2] * 8)
        assert (a != c).any()  # different seeds, different draws
        d = self._pick(lg, [3.0] * 8, [0] * 8, [0.0] * 8,
                       list(range(8)), [3] * 8)
        assert (a != d).any()  # different positions, different draws

    def test_seed_key_matches_prngkey(self):
        """The drift guard: the host-side key layout must equal the
        real ``jax.random.PRNGKey`` for every legal seed."""
        for seed in (0, 1, 42, 2**20 + 17, S.MAX_SEED - 1):
            np.testing.assert_array_equal(
                S.seed_key(seed), np.asarray(jax.random.PRNGKey(seed)))

    def test_validate_rejects_bad_params(self):
        with pytest.raises(serving.ServingError):
            S.validate(temperature=-0.5)
        with pytest.raises(serving.ServingError):
            S.validate(temperature=float("nan"))
        with pytest.raises(serving.ServingError):
            S.validate(top_k=-1)
        with pytest.raises(serving.ServingError):
            S.validate(top_p=1.5)
        with pytest.raises(serving.ServingError):
            S.validate(seed=-1)
        with pytest.raises(serving.ServingError):
            S.validate(seed=S.MAX_SEED)
        with pytest.raises(serving.ServingError):
            S.validate(temperature="hot")
        assert S.validate(1.0, 5, 0.9, 7) == (1.0, 5, 0.9, 7)
        assert S.validate() == (0.0, 0, 0.0, 0)

    def test_slot_sampling_upload_caching(self):
        cols = serving.SlotSampling(3)
        d1 = cols.device()
        assert cols.device() is d1  # clean: cached
        cols.set(1, temperature=0.8, top_k=3, top_p=0.9, seed=11)
        d2 = cols.device()
        assert d2 is not d1
        assert float(d2[0][1]) == pytest.approx(0.8)
        np.testing.assert_array_equal(np.asarray(d2[3][1]), [0, 11])
        cols.clear(1)
        assert float(cols.device()[0][1]) == 0.0


# ---------------------------------------------------------------------------
# the oracle itself
# ---------------------------------------------------------------------------


class TestSampleDecodeOracle:
    def test_temperature_zero_is_greedy_with_top_p(self, model):
        params, cfg = model
        prompt = jnp.asarray([[3, 4, 5]], jnp.int32)
        g = np.asarray(T.greedy_decode(params, prompt, 5, cfg))
        s = np.asarray(T.sample_decode(
            params, prompt, 5, cfg, rng=jax.random.PRNGKey(1),
            temperature=0.0, top_p=0.9))
        np.testing.assert_array_equal(g, s)

    def test_continuation_identity(self, model):
        """The resume/failover contract at the oracle level: sampling
        ``prompt + first_half`` with the same rng continues the exact
        stream — keys depend on token POSITION, not the prefill
        split."""
        params, cfg = model
        kw = dict(rng=jax.random.PRNGKey(9), temperature=1.3, top_k=8,
                  top_p=0.9)
        prompt = jnp.asarray([[7, 8, 9]], jnp.int32)
        full = np.asarray(T.sample_decode(params, prompt, 8, cfg, **kw))
        head = np.asarray(T.sample_decode(params, prompt, 3, cfg, **kw))
        grown = jnp.concatenate(
            [prompt, jnp.asarray(head, jnp.int32)], axis=1)
        tail = np.asarray(T.sample_decode(params, grown, 5, cfg, **kw))
        np.testing.assert_array_equal(
            np.concatenate([head, tail], axis=1), full)

    def test_batch_rows_draw_independent_streams(self, model):
        params, cfg = model
        prompt = jnp.asarray([[3, 4, 5], [3, 4, 5]], jnp.int32)
        out = np.asarray(T.sample_decode(
            params, prompt, 8, cfg, rng=jax.random.PRNGKey(2),
            temperature=1.5))
        assert (out[0] != out[1]).any()


# ---------------------------------------------------------------------------
# the engine: mixed-parameter batches == per-request oracle
# ---------------------------------------------------------------------------


MIX = [
    ([3, 4, 5], dict()),                                     # greedy
    ([7, 8], dict(temperature=1.1, seed=5)),                 # temp only
    ([1, 2, 3, 4], dict(temperature=0.7, top_k=5, seed=9)),  # top-k
    ([9], dict(temperature=1.5, top_p=0.8, seed=13)),        # top-p
]


class TestEngineSampling:
    @pytest.mark.perf
    @pytest.mark.slow
    def test_mixed_batch_matches_oracle_zero_recompiles(self, model):
        """THE acceptance property: one compiled decode executable
        serves mixed greedy/temperature/top-k/top-p traffic, each
        slot's stream token-identical to ``sample_decode`` at its own
        seed, with zero decode recompiles across churn.  Slow (PR 17
        budget pass): two full waves of the 4-way mix are ~16 s; the
        sampled-prefix-sharers and restart-resume tests below keep
        engine-level per-seed oracle identity tier-1."""
        params, cfg = model
        eng = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=4, max_len=32, tick_timeout=0))
        eng.warmup([1, 4])
        base = eng.decode_compilations
        # two waves of churn over the same slots
        for wave in range(2):
            futs = [eng.submit(p, max_new_tokens=8, **kw)
                    for p, kw in MIX]
            _run(eng, futs)
            for (p, kw), f in zip(MIX, futs):
                assert f.result(1) == _oracle(params, cfg, p, 8, **kw), \
                    f"wave {wave}, params {kw}"
        assert eng.decode_compilations == base, \
            "sampling parameter mix recompiled the decode tick"

    @pytest.mark.slow
    def test_sync_and_contiguous_modes_match_oracle(self, model):
        # Slow (PR 17 budget pass): builds two more engine variants,
        # ~14 s; the default-mode (overlap+paged) oracle tests stay
        # tier-1 and test_serving covers the sync/contiguous ticks.
        params, cfg = model
        for ec in (serving.EngineConfig(n_slots=4, max_len=32,
                                        overlap=False, tick_timeout=0),
                   serving.EngineConfig(n_slots=4, max_len=32,
                                        paged=False, tick_timeout=0)):
            eng = serving.InferenceEngine(params, cfg, ec)
            eng.warmup([1, 4])
            futs = [eng.submit(p, max_new_tokens=6, **kw)
                    for p, kw in MIX[:3]]
            _run(eng, futs)
            for (p, kw), f in zip(MIX, futs):
                assert f.result(1) == _oracle(params, cfg, p, 6, **kw)

    def test_sampled_prefix_sharers_draw_own_tokens(self, model):
        """Attach-only admission (prompt == registered prefix) must
        give each SAMPLED sharer its own first token from the cached
        prefix logits — not the cached greedy token."""
        params, cfg = model
        eng = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=4, max_len=32, tick_timeout=0))
        eng.warmup([1, 4])
        prefix = [5, 6, 7, 8]
        eng.register_prefix(prefix)
        futs = [eng.submit(prefix, max_new_tokens=6,
                           temperature=1.4, seed=s) for s in (3, 17)]
        futs.append(eng.submit(prefix, max_new_tokens=6))  # greedy
        _run(eng, futs)
        for s, f in zip((3, 17), futs[:2]):
            assert f.result(1) == _oracle(params, cfg, prefix, 6,
                                          temperature=1.4, seed=s)
        assert futs[2].result(1) == _oracle(params, cfg, prefix, 6)
        assert [f.result(1) for f in futs[:2]][0] != \
            [f.result(1) for f in futs[:2]][1]

    def test_restart_resume_keeps_sampled_stream(self, model):
        """Crash mid-decode: resumed sampled output is token-identical
        to an uninterrupted run — the journal carries the sampling
        params and the position-keyed PRNG continues the stream."""
        params, cfg = model
        faults = FaultInjector()
        eng = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=4, max_len=32, tick_timeout=0, faults=faults))
        eng.warmup([1, 4])
        faults.add(FaultSpec(site="decode_tick", kind="raise",
                             skip=faults.visits("decode_tick") + 4))
        subs = [([3, 4, 5], dict(temperature=1.3, top_k=8, top_p=0.9,
                                 seed=21)),
                ([7, 8], dict(temperature=0.9, seed=4))]
        futs = [eng.submit(p, max_new_tokens=10, **kw)
                for p, kw in subs]
        _run(eng, futs)
        assert eng.metrics.resumed.value >= 1
        for (p, kw), f in zip(subs, futs):
            assert f.result(1) == _oracle(params, cfg, p, 10, **kw)

    @pytest.mark.slow
    def test_speculative_mixed_sampled_and_greedy(self, model):
        """On a speculative engine a sampled request emits exactly its
        oracle stream (drafts never accepted for it — acceptance
        forced to 0 as data) while greedy slots keep speculating; the
        compile count stays at the spec engine's two executables.
        Slow (PR 17 budget pass): the spec engine build is ~11 s;
        test_speculative's spec_on-mask kernel unit keeps the
        forced-greedy acceptance path tier-1."""
        params, cfg = model
        eng = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=4, max_len=32, speculative=True, spec_k=3,
            spec_draft="ngram", spec_adaptive=False, tick_timeout=0))
        eng.warmup([1, 4])
        base = eng.decode_compilations
        subs = [([3, 4, 5], dict()),
                ([7, 8], dict(temperature=1.1, seed=5)),
                ([1, 2, 3, 4], dict(temperature=0.7, top_k=5, seed=9))]
        futs = [eng.submit(p, max_new_tokens=8, **kw)
                for p, kw in subs]
        _run(eng, futs)
        for (p, kw), f in zip(subs, futs):
            assert f.result(1) == _oracle(params, cfg, p, 8, **kw)
        assert eng.decode_compilations == base

    def test_submit_validation_and_defaults(self, model):
        params, cfg = model
        eng = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=2, max_len=32, tick_timeout=0))
        with pytest.raises(serving.ServingError):
            eng.submit([1], temperature=-1.0)
        with pytest.raises(serving.ServingError):
            eng.submit([1], top_p=2.0)
        with pytest.raises(serving.ServingError):
            eng.submit([1], seed=-5)


# ---------------------------------------------------------------------------
# journal round trip
# ---------------------------------------------------------------------------


class TestJournalSampling:
    def test_begin_and_read_live_round_trip(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = serving.RequestJournal(path)
        fut = serving.GenerationFuture()
        import horovod_tpu.obs.tracing as obs_tracing

        fut.trace = obs_tracing.RequestTrace("a" * 16)
        req = serving.Request(prompt=[1, 2], max_new_tokens=8,
                              future=fut, eos_id=3, trace=fut.trace,
                              temperature=1.25, top_k=4, top_p=0.75,
                              seed=99)
        j.begin(req)
        j.append(req.id, 7)
        live = serving.RequestJournal.read_live(path)
        d = live["a" * 16]
        assert d["emitted_tokens"] == [7]
        assert d["temperature"] == 1.25 and d["seed"] == 99
        entry = j.get(req.id)
        assert (entry.temperature, entry.top_k, entry.top_p,
                entry.seed) == (1.25, 4, 0.75, 99)

    def test_greedy_begin_line_stays_compact(self, tmp_path):
        path = str(tmp_path / "g.jsonl")
        j = serving.RequestJournal(path)
        fut = serving.GenerationFuture()
        req = serving.Request(prompt=[1], max_new_tokens=2, future=fut)
        j.begin(req)
        import json as _json

        line = _json.loads(open(path).read().splitlines()[0])
        assert "samp" not in line  # greedy journals stay pre-sampling
