"""SLO-aware scheduling (docs/serving.md "Scheduling"): chunked
prefill interleaved with decode, priority classes + EDF ordering, and
preemption under slot/page pressure.

The gold checks:

* CHUNKED prefill is invisible in the output: greedy AND sampled
  engine output with ``prefill_chunk_tokens`` set is token-identical
  to the whole-prompt oracle (``greedy_decode`` / ``sample_decode``),
  with the decode executable still compiled exactly once — chunk
  boundaries are data, never structure.
* Decode RIDES THROUGH ingestion: a short request admitted behind a
  long prompt finishes before the long prompt's first token — the
  prefill/decode interference chunking exists to kill.
* PREEMPTION is a suspension, not a loss: the victim's future stays
  live, it re-admits from its journal frontier, and its final output
  is byte-identical to an uninterrupted run — composed with COW
  prefix sharing (refcounts balance) and SSE streaming (the stream
  continues gapless).
* A lapsed-deadline request resolves at the NEXT TICK BOUNDARY
  (``Scheduler.sweep``), not whenever admission happens to reach it.
"""

import dataclasses
import http.client
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving import sse
from horovod_tpu.serving.faults import FaultInjector, FaultSpec
from horovod_tpu.serving.journal import RequestJournal
from horovod_tpu.serving.scheduler import (
    DeadlineExceededError,
    Request,
    Scheduler,
    ServingError,
    priority_rank,
)
from horovod_tpu.serving.server import ServingServer

pytestmark = [pytest.mark.serving, pytest.mark.sched]


def _cfg(**kw):
    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=96, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _ref_sampled(params, cfg, prompt, steps, *, temperature, top_k=0,
                 top_p=0.0, seed=0):
    return np.asarray(T.sample_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg,
        rng=jax.random.PRNGKey(seed), temperature=temperature,
        top_k=top_k, top_p=top_p))[0].tolist()


def _run_until_done(engine, futs, max_ticks=800):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


def _engine(params, cfg, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("max_len", 96)
    kw.setdefault("min_prefill_bucket", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("tick_timeout", 0)  # stepped engines: no watchdog
    return serving.InferenceEngine(params, cfg,
                                   serving.EngineConfig(**kw))


class _F:
    """Minimal future stub for scheduler-only tests."""

    cancel_requested = False

    def __init__(self):
        self.exc = None
        self.reason = None
        self._d = False

    def done(self):
        return self._d

    def set_exception(self, e):
        self.exc, self._d = e, True

    def _finish(self, reason):
        self.reason, self._d = reason, True


def _req(**kw):
    kw.setdefault("prompt", [1])
    kw.setdefault("max_new_tokens", 1)
    kw.setdefault("future", _F())
    return Request(**kw)


# ---------------------------------------------------------------------------
# scheduler ordering (pure unit)
# ---------------------------------------------------------------------------


class TestSchedulerOrdering:
    def test_priority_class_before_submission_order(self):
        s = Scheduler(max_prefills_per_tick=8)
        batch = _req(prompt=[1], priority="batch")
        inter = _req(prompt=[2], priority="interactive")
        s.submit(batch)
        s.submit(inter)  # submitted later, served first
        out = s.take(free_slots=4)
        assert [r.prompt for r in out] == [[2], [1]]

    def test_edf_within_class(self):
        clock = [0.0]
        s = Scheduler(clock=lambda: clock[0], max_prefills_per_tick=8)
        late = _req(prompt=[1], deadline=100.0)
        soon = _req(prompt=[2], deadline=5.0)
        none = _req(prompt=[3])  # no deadline: after every deadline
        for r in (none, late, soon):
            s.submit(r)
        out = s.take(free_slots=4)
        assert [r.prompt for r in out] == [[2], [1], [3]]

    def test_edf_never_crosses_class(self):
        clock = [0.0]
        s = Scheduler(clock=lambda: clock[0], max_prefills_per_tick=8)
        urgent_batch = _req(prompt=[1], priority="batch", deadline=1.0)
        lazy_inter = _req(prompt=[2], deadline=1000.0)
        s.submit(urgent_batch)
        s.submit(lazy_inter)
        out = s.take(free_slots=4)
        assert [r.prompt for r in out] == [[2], [1]]

    def test_fcfs_tiebreak_within_class(self):
        s = Scheduler(max_prefills_per_tick=8)
        a, b = _req(prompt=[1]), _req(prompt=[2])
        s.submit(a)
        s.submit(b)
        assert [r.prompt for r in s.take(4)] == [[1], [2]]

    def test_bucket_uniform_truncates_in_order(self):
        s = Scheduler(max_prefills_per_tick=4)
        a = _req(prompt=[1] * 4)
        b = _req(prompt=[2] * 16)
        c = _req(prompt=[3] * 4)
        for r in (a, b, c):
            s.submit(r)
        out = s.take(4, bucket_fn=lambda r: len(r.prompt))
        # the head's bucket wins; the first mismatch stops the take —
        # c is NOT pulled around b (order truncated, never violated)
        assert [r.prompt[0] for r in out] == [1]

    def test_peek_best_rank_skips_dead(self):
        clock = [0.0]
        s = Scheduler(clock=lambda: clock[0])
        doomed = _req(prompt=[1], deadline=1.0)  # interactive but dead
        alive = _req(prompt=[2], priority="batch")
        s.submit(doomed)
        s.submit(alive)
        clock[0] = 2.0
        assert s.peek_best_rank() == priority_rank("batch")

    def test_sweep_resolves_lapsed_behind_live_head(self):
        """SATELLITE regression: a lapsed request BEHIND the order
        head (a worse class — within a class EDF puts lapsed
        deadlines first) resolves promptly wherever it sits: sweep()
        scans the WHOLE queue, and a zero-budget take() routes
        through the same sweep instead of stopping at the live
        head."""
        clock = [0.0]
        rejected = []
        s = Scheduler(clock=lambda: clock[0],
                      on_reject=lambda r, e: rejected.append(r))
        live = _req(prompt=[1])  # interactive: the order head
        doomed = _req(prompt=[2], priority="batch", deadline=1.0)
        s.submit(live)
        s.submit(doomed)
        clock[0] = 2.0
        # a zero-budget take is a cheap no-op: dead resolution is the
        # sweep's job (the engine runs it at every tick boundary)
        assert s.take(free_slots=0) == []
        assert not doomed.future.done()
        assert s.sweep() == 1              # resolved behind the head
        assert isinstance(doomed.future.exc, DeadlineExceededError)
        assert rejected == [doomed]        # metrics hook fired
        assert s.depth == 1                # the live head stays

    def test_requeued_victim_deadline_finishes_partial(self):
        """REGRESSION (review): a preempted victim waiting to
        re-admit already served tokens — a deadline lapsing in the
        queue must FINISH it with the partial result (the
        deadline-after-admission contract), never 504 away paid-for
        output."""
        clock = [0.0]
        expired = []
        s = Scheduler(clock=lambda: clock[0],
                      on_expire=lambda r: expired.append(r))
        fut = _F()
        fut.ttft = 0.01  # admitted once: a previous life emitted
        victim = _req(prompt=[1, 2, 7], future=fut, deadline=1.0)
        s.requeue_front([victim])
        clock[0] = 2.0
        assert s.sweep() == 1
        assert fut.exc is None and fut.reason == "deadline"
        assert expired == [victim]
        # ... and a victim preempted MID-INGESTION (admitted, no token
        # yet, so no ttft — only trace.admitted_at) gets the same
        # finish: its uninterrupted twin would have lapsed in-slot
        fut2 = _F()
        victim2 = _req(prompt=[3, 4], future=fut2, deadline=1.5)
        victim2.trace = type("Tr", (), {"admitted_at": 0.5})()
        s.requeue_front([victim2])
        assert s.sweep() == 1
        assert fut2.exc is None and fut2.reason == "deadline"
        assert expired == [victim, victim2]

    def test_requeued_no_deadline_victim_not_starved_by_edf(self):
        """REGRESSION (review): a preempted victim WITHOUT a deadline
        must not sort behind every deadlined same-class arrival
        forever — the requeue boost puts it ahead of everything
        non-requeued in its class."""
        s = Scheduler(max_prefills_per_tick=8, clock=lambda: 0.0)
        victim = _req(prompt=[1])          # no deadline
        s.requeue_front([victim])
        rival = _req(prompt=[2], deadline=5.0)  # EDF-favored arrival
        s.submit(rival)
        out = s.take(free_slots=4)
        assert [r.prompt for r in out] == [[1], [2]]

    def test_unknown_priority_rejected(self):
        with pytest.raises(ServingError):
            priority_rank("platinum")


# ---------------------------------------------------------------------------
# tick-boundary deadline sweep (engine level)
# ---------------------------------------------------------------------------


class TestDeadlineSweep:
    def test_doomed_request_resolves_during_admission_stall(self, model):
        """A queued request whose deadline lapses while every slot is
        busy (and a live request is queued AHEAD of it) gets its 504
        within a tick — it does not wait for the stall to clear."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=1)
        busy = engine.submit([1, 2, 3], max_new_tokens=60)
        for _ in range(4):
            engine.step()
        ahead = engine.submit([4, 5], max_new_tokens=2)
        doomed = engine.submit(
            [6, 7], max_new_tokens=2, priority="batch",
            deadline=time.monotonic() + 0.03)
        time.sleep(0.05)
        engine.step()  # one tick boundary: the sweep runs
        assert doomed.done() and not ahead.done() and not busy.done()
        with pytest.raises(serving.DeadlineExceededError):
            doomed.result(timeout=0)
        _run_until_done(engine, [busy, ahead])


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------


class TestChunkedPrefill:
    def test_chunked_requires_paged(self, model):
        params, cfg = model
        with pytest.raises(ValueError):
            serving.InferenceEngine(params, cfg, serving.EngineConfig(
                paged=False, prefill_chunk_tokens=8))

    @pytest.mark.slow
    def test_chunked_greedy_oracle_overlap(self, model):
        """Mixed long/short greedy traffic, chunked: token-identical
        to the whole-prompt oracle; ONE decode compile (chunk
        boundaries are data).  Slow (PR 17 budget pass): the 4-prompt
        mixed-length A/B is ~13 s; the sync-mode single-prompt oracle
        below keeps the same property tier-1."""
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8)
        rng = np.random.default_rng(7)
        prompts = [[int(t) for t in rng.integers(1, 64, n)]
                   for n in (41, 3, 27, 5)]
        futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
        _run_until_done(engine, futs)
        for p, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, 8)
        assert engine.decode_compilations == 1
        assert engine.stats()["slots_ingesting"] == 0

    def test_chunked_greedy_oracle_sync(self, model):
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8,
                         overlap=False)
        rng = np.random.default_rng(9)
        p = [int(t) for t in rng.integers(1, 64, 37)]
        fut = engine.submit(p, max_new_tokens=6)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg, p, 6)
        assert engine.decode_compilations == 1

    @pytest.mark.slow
    def test_chunked_sampled_oracle(self, model):
        """A SAMPLED long prompt: the final chunk's logits feed the
        first draw at key index len(prompt), so the stream matches
        sample_decode exactly — chunking never touches the PRNG
        schedule.  Slow (PR 17 budget pass): the greedy chunked
        oracles here plus test_sampling's engine-level PRNG oracles
        keep both halves of the property tier-1."""
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8)
        rng = np.random.default_rng(11)
        p = [int(t) for t in rng.integers(1, 64, 33)]
        fut = engine.submit(p, max_new_tokens=8, temperature=0.8,
                            top_k=12, seed=13)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_sampled(
            params, cfg, p, 8, temperature=0.8, top_k=12, seed=13)

    def test_chunked_attends_shared_prefix(self, model):
        """Chunked ingestion composes with COW prefix sharing: the
        prefix pages attach (no compute), the chunks land only the
        suffix, output matches the oracle, and every page recycles
        after retirement (the pin stays)."""
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8)
        prefix = [9, 8, 7, 6, 5, 4, 3, 2]
        engine.register_prefix(prefix)
        pinned = len(engine._prefixes[tuple(prefix)].pages)
        rng = np.random.default_rng(13)
        suffix = [int(t) for t in rng.integers(1, 64, 30)]
        p = prefix + suffix
        fut = engine.submit(p, max_new_tokens=6)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg, p, 6)
        assert engine.slots.free_pages == engine.slots.n_pages - pinned
        assert engine.slots.pages_shared == 0  # nothing left attached

    def test_decode_rides_through_ingestion(self, model):
        """THE Sarathi property: a short request admitted behind a
        long prompt decodes to completion while the long prompt is
        still ingesting — whole-prompt prefill would have stalled it
        for the full prompt."""
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8)
        rng = np.random.default_rng(17)
        long_p = [int(t) for t in rng.integers(1, 64, 64)]
        long_fut = engine.submit(long_p, max_new_tokens=4)
        engine.step()  # first chunk lands; ingestion is under way
        short_fut = engine.submit([5, 9], max_new_tokens=3)
        for _ in range(400):
            engine.step()
            if short_fut.done():
                break
        assert short_fut.done()
        # the long prompt is still ingesting: no first token yet
        assert not long_fut.done()
        assert long_fut.tokens_so_far() == []
        assert short_fut.result(timeout=0) == _ref_greedy(
            params, cfg, [5, 9], 3)
        _run_until_done(engine, [long_fut])
        assert long_fut.result(timeout=0) == _ref_greedy(
            params, cfg, long_p, 4)

    @pytest.mark.perf
    def test_chunk_compile_set_is_bounded(self, model):
        """Chunk boundaries are DATA: a second long prompt of the same
        length re-uses every chunk executable (no new prefill traces),
        and decode never recompiles."""
        params, cfg = model
        engine = _engine(params, cfg, prefill_chunk_tokens=8)
        rng = np.random.default_rng(19)
        p1 = [int(t) for t in rng.integers(1, 64, 43)]
        fut = engine.submit(p1, max_new_tokens=4)
        _run_until_done(engine, [fut])
        traces = engine._prefill_traces
        decode = engine.decode_compilations
        p2 = [int(t) for t in rng.integers(1, 64, 43)]
        fut2 = engine.submit(p2, max_new_tokens=4)
        _run_until_done(engine, [fut2])
        assert engine._prefill_traces == traces
        assert engine.decode_compilations == decode == 1
        assert fut2.result(timeout=0) == _ref_greedy(params, cfg, p2, 4)


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------


class TestPreemption:
    def test_slot_pressure_suspends_batch_for_interactive(self, model):
        """Every slot busy with batch work + an interactive arrival:
        the youngest batch occupant SUSPENDS (live future, journal
        frontier), the interactive request admits promptly, and the
        victim's final output is byte-identical to an uninterrupted
        run."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2)
        b1 = engine.submit([1, 2, 3], max_new_tokens=24,
                           priority="batch")
        b2 = engine.submit([4, 5, 6], max_new_tokens=24,
                           priority="batch")
        for _ in range(6):
            engine.step()
        assert not b1.done() and not b2.done()
        inter = engine.submit([7, 8, 9], max_new_tokens=3)
        for _ in range(40):
            engine.step()
            if inter.done():
                break
        assert inter.done()          # admitted well before a batch slot
        assert not (b1.done() and b2.done())  # one was suspended
        assert engine.stats()["preemptions"] >= 1
        _run_until_done(engine, [b1, b2])
        assert b1.result(timeout=0) == _ref_greedy(
            params, cfg, [1, 2, 3], 24)
        assert b2.result(timeout=0) == _ref_greedy(
            params, cfg, [4, 5, 6], 24)

    def test_no_preemption_within_class(self, model):
        """Equal classes wait FCFS: an interactive arrival never
        suspends an interactive occupant."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=1)
        first = engine.submit([1, 2, 3], max_new_tokens=12)
        for _ in range(4):
            engine.step()
        second = engine.submit([4, 5], max_new_tokens=2)
        _run_until_done(engine, [first, second])
        assert engine.stats()["preemptions"] == 0
        assert first.result(timeout=0) == _ref_greedy(
            params, cfg, [1, 2, 3], 12)
        assert second.result(timeout=0) == _ref_greedy(
            params, cfg, [4, 5], 2)

    @pytest.mark.slow
    def test_preemption_cow_refcounts_balance(self, model):
        """COMPOSITION: preempting a victim that shares COW prefix
        pages decrefs exactly its references — after everything
        retires the pool is back to the pin, and the prefix stays
        servable.  Slow (PR 17 budget pass): ~8 s; test_paged's
        resume/COW refcount-balance tests keep the refcount invariant
        tier-1."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2)
        prefix = [9, 8, 7, 6, 5, 4, 3, 2]
        engine.register_prefix(prefix)
        pinned = len(engine._prefixes[tuple(prefix)].pages)
        b1 = engine.submit(prefix + [1], max_new_tokens=20,
                           priority="batch")
        b2 = engine.submit(prefix + [2], max_new_tokens=20,
                           priority="batch")
        for _ in range(6):
            engine.step()
        inter = engine.submit(prefix + [3], max_new_tokens=3)
        _run_until_done(engine, [inter, b1, b2])
        assert engine.stats()["preemptions"] >= 1
        assert inter.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + [3], 3)
        assert b1.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + [1], 20)
        assert b2.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + [2], 20)
        assert engine.slots.free_pages == engine.slots.n_pages - pinned
        assert engine.slots.pages_shared == 0

    @pytest.mark.slow
    def test_preempted_streaming_client_sees_gapless_stream(self, model):
        """Slow (PR 17 budget pass): HTTP server + live SSE stream is
        ~6 s; the non-streamed preemption tests here and
        test_streaming's in-process mid-stream continuation keep both
        halves of the composition tier-1.

        COMPOSITION: a STREAMED batch request that gets preempted
        resumes on the same engine with the same live future — the
        client's SSE stream pauses, then continues with gapless
        indices and finishes byte-identical to the oracle."""
        params, cfg = model
        engine = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=1, max_len=96, min_prefill_bucket=4, page_size=8))
        srv = ServingServer(engine, port=0)
        srv.start()
        try:
            host, port = srv.address
            c = http.client.HTTPConnection(host, port, timeout=60)
            c.request("POST", "/generate", body=json.dumps({
                "tokens": [1, 2, 3], "max_new_tokens": 16,
                "priority": "batch", "stream": True}).encode())
            resp = c.getresponse()
            assert resp.status == 200
            # wait until the stream is live, then put it under slot
            # pressure from an interactive request
            deadline = time.monotonic() + 20
            while engine.metrics.streamed_tokens.value == 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            c2 = http.client.HTTPConnection(host, port, timeout=60)
            c2.request("POST", "/generate", body=json.dumps({
                "tokens": [7, 8], "max_new_tokens": 2}).encode())
            r2 = c2.getresponse()
            assert r2.status == 200
            out2 = json.loads(r2.read())
            assert out2["tokens"] == _ref_greedy(params, cfg, [7, 8], 2)
            events = sse.read_stream(resp)
            toks = [p["token"] for k, p in events if k == "token"]
            idxs = [p["i"] for k, p in events if k == "token"]
            done = [p for k, p in events if k == "done"]
            assert len(done) == 1
            assert idxs == list(range(len(toks)))  # gapless
            assert toks == done[0]["tokens"] == _ref_greedy(
                params, cfg, [1, 2, 3], 16)
            assert engine.stats()["preemptions"] >= 1
        finally:
            srv.stop(drain_timeout=10)

    def test_chunked_ingestion_preempted_resumes_exact(self, model):
        """COMPOSITION: the victim is MID-INGESTION (no tokens emitted
        yet) — suspension frees its chunk pages and the re-admission
        re-ingests from the original prompt, oracle-exact."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=1,
                         prefill_chunk_tokens=8)
        rng = np.random.default_rng(23)
        long_p = [int(t) for t in rng.integers(1, 64, 48)]
        victim = engine.submit(long_p, max_new_tokens=4,
                               priority="batch")
        engine.step()  # a chunk or two land
        engine.step()
        assert engine.stats()["slots_ingesting"] == 1
        inter = engine.submit([5, 6], max_new_tokens=2)
        _run_until_done(engine, [inter, victim])
        assert engine.stats()["preemptions"] >= 1
        # the landed-but-discarded chunks count as wasted re-prefill
        # work (the journal alone cannot see them)
        assert engine.stats()["resume_wasted_tokens"] >= 8
        assert inter.result(timeout=0) == _ref_greedy(
            params, cfg, [5, 6], 2)
        assert victim.result(timeout=0) == _ref_greedy(
            params, cfg, long_p, 4)

    @pytest.mark.slow
    def test_chunked_first_token_retire_on_model_draft_engine(self,
                                                              model):
        """Slow (PR 17 budget pass): builds a second (model-draft
        speculative) engine, ~11 s; the plain-engine preemption and
        chunked-retire tests above keep the slot-lifecycle invariants
        tier-1.

        REGRESSION (review): a chunked request whose FIRST token
        retires it (max_new_tokens=1) on a model-draft speculative
        engine — the draft-slot acquire must happen before the emit
        can free the slot, or the freed slot is re-activated with no
        owner and the next tenant crashes the tick."""
        params, cfg = model
        dcfg = dataclasses.replace(cfg, n_layers=1)
        dparams = T.init_params(jax.random.PRNGKey(1), dcfg)
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=2, max_len=96, min_prefill_bucket=4,
                page_size=8, tick_timeout=0, prefill_chunk_tokens=8,
                speculative=True, spec_k=2, spec_draft="model"),
            draft_params=dparams, draft_cfg=dcfg)
        rng = np.random.default_rng(41)
        p1 = [int(t) for t in rng.integers(1, 64, 30)]
        f1 = engine.submit(p1, max_new_tokens=1)
        _run_until_done(engine, [f1])
        assert f1.result(timeout=0) == _ref_greedy(params, cfg, p1, 1)
        # the same slot must be reusable by the next chunked tenant
        p2 = [int(t) for t in rng.integers(1, 64, 30)]
        f2 = engine.submit(p2, max_new_tokens=4)
        _run_until_done(engine, [f2])
        assert f2.result(timeout=0) == _ref_greedy(params, cfg, p2, 4)
        # ... and a chunked admission never pays a one-tick
        # whole-prompt DRAFT prefill (the slot degrades to plain
        # greedy instead): no draft-prefill compile shapes exist
        assert engine._draft_prefill_fns == {}


# ---------------------------------------------------------------------------
# chunked prefill x restart-resume (crash mid-chunk)
# ---------------------------------------------------------------------------


class TestChunkedResume:
    @pytest.mark.slow
    def test_crash_mid_chunk_resumes_oracle_exact(self, model):
        """Slow (PR 17 budget pass): restart + re-ingest is ~9 s;
        test_chunked_ingestion_preempted_resumes_exact keeps the
        suspend-mid-ingestion/re-ingest-exact path tier-1, and
        tests/test_chaos.py runs this same fault site under the full
        chaos invariant.

        A tick failure at a CHUNK boundary suspends the ingesting
        request through the ordinary resume path; the restart
        re-ingests from scratch and the output is token-identical to
        an uninterrupted run (tests/test_chaos.py runs the same site
        under the full chaos invariant)."""
        params, cfg = model
        inj = FaultInjector([FaultSpec(site="prefill_chunk",
                                       kind="raise", skip=2)])
        engine = _engine(params, cfg, prefill_chunk_tokens=8,
                         faults=inj, restart_backoff=0.01)
        rng = np.random.default_rng(29)
        long_p = [int(t) for t in rng.integers(1, 64, 40)]
        short = engine.submit([3, 4], max_new_tokens=3)
        victim = engine.submit(long_p, max_new_tokens=5,
                               priority="batch")
        _run_until_done(engine, [short, victim])
        assert inj.fired and inj.fired[0][0] == "prefill_chunk"
        assert engine.stats()["engine_restarts"] == 1
        assert victim.result(timeout=0) == _ref_greedy(
            params, cfg, long_p, 5)
        assert short.result(timeout=0) == _ref_greedy(
            params, cfg, [3, 4], 3)


# ---------------------------------------------------------------------------
# plumbing: per-class metrics, HTTP priority, journal round-trip
# ---------------------------------------------------------------------------


class TestPriorityPlumbing:
    def test_per_class_metrics_and_stats(self, model):
        params, cfg = model
        engine = _engine(params, cfg)
        fi = engine.submit([1, 2], max_new_tokens=2)
        fb = engine.submit([3, 4], max_new_tokens=2, priority="batch")
        _run_until_done(engine, [fi, fb])
        s = engine.stats()
        assert s["ttft_seconds_by_class"]["interactive"]["count"] == 1
        assert s["ttft_seconds_by_class"]["batch"]["count"] == 1
        assert s["ttft_seconds"]["count"] == 2  # merged, historical key
        assert s["queue_wait_seconds_by_class"]["batch"]["count"] == 1
        assert s["preemptions"] == 0
        text = engine.metrics.registry.to_prometheus()
        assert 'serving_ttft_seconds_count{class="batch"}' in text
        assert 'serving_queue_wait_seconds_count{class="interactive"}' \
            in text
        assert "serving_preemptions_total" in text

    def test_unknown_priority_is_typed_rejection(self, model):
        params, cfg = model
        engine = _engine(params, cfg)
        with pytest.raises(ServingError):
            engine.submit([1], max_new_tokens=1, priority="platinum")

    def test_http_priority_roundtrip_and_400(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(params, cfg, serving.EngineConfig(
            n_slots=2, max_len=96, min_prefill_bucket=4))
        srv = ServingServer(engine, port=0)
        srv.start()
        try:
            host, port = srv.address
            c = http.client.HTTPConnection(host, port, timeout=30)
            c.request("POST", "/generate", body=json.dumps({
                "tokens": [1, 2], "max_new_tokens": 2,
                "priority": "batch"}).encode())
            r = c.getresponse()
            assert r.status == 200
            assert json.loads(r.read())["tokens"] == _ref_greedy(
                params, cfg, [1, 2], 2)
            assert engine.stats()[
                "ttft_seconds_by_class"]["batch"]["count"] == 1
            c.request("POST", "/generate", body=json.dumps({
                "tokens": [1, 2], "max_new_tokens": 2,
                "priority": "platinum"}).encode())
            r = c.getresponse()
            assert r.status == 400
            r.read()
        finally:
            srv.stop(drain_timeout=10)

    def test_journal_roundtrips_priority(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = RequestJournal(path)
        req = _req(prompt=[1, 2], max_new_tokens=4, priority="batch")
        req.trace = type("Tr", (), {"trace_id": "t" * 32,
                                    "span_id": None})()
        j.begin(req)
        j.append(req.id, 7)
        live = RequestJournal.read_live(path)
        assert live["t" * 32]["priority"] == "batch"
        assert live["t" * 32]["emitted_tokens"] == [7]
        # default class stays off the wire (pre-priority readers)
        req2 = _req(prompt=[3], max_new_tokens=1)
        req2.trace = type("Tr", (), {"trace_id": "u" * 32,
                                     "span_id": None})()
        j.begin(req2)
        with open(path) as f:
            lines = [json.loads(l) for l in f if l.strip()]
        assert "pri" not in lines[-1]
        assert RequestJournal.read_live(path)[
            "u" * 32]["priority"] == "interactive"

    def test_priority_survives_restart_resume(self, model):
        """A batch-class request interrupted by an engine crash
        resumes as batch (journal + _build_resume carry the class)."""
        params, cfg = model
        inj = FaultInjector([FaultSpec(site="decode_tick",
                                       kind="raise", skip=6)])
        engine = _engine(params, cfg, faults=inj,
                         restart_backoff=0.01)
        fut = engine.submit([1, 2, 3], max_new_tokens=10,
                            priority="batch")
        _run_until_done(engine, [fut])
        assert engine.stats()["engine_restarts"] == 1
        assert engine.stats()["requests_resumed"] == 1
        assert fut.result(timeout=0) == _ref_greedy(
            params, cfg, [1, 2, 3], 10)
        # per-class TTFT was observed once, in the batch class
        assert engine.stats()[
            "ttft_seconds_by_class"]["batch"]["count"] == 1
