"""The eager/native control-plane benchmark harness must run end to end
and reproduce its headline direction (native fusion beats the direct
path under many-small-tensor load) at smoke scale."""

import json
import os
import subprocess
import sys

import pytest

from horovod_tpu import native

pytestmark = [pytest.mark.perf,  # bench-shaped: drives a benchmarks/ script
              pytest.mark.slow]  # tier-1 budget: see tests/DURATIONS.md

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
def test_native_beats_direct_smoke(tmp_path):
    # Full env passthrough: the workers' XLA CPU runtime behaves
    # differently under a stripped environment (thread/cache config),
    # which skews the direct/native ratio.
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "PALLAS_AXON_POOL_IPS": "",
    }
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "eager_fusion.py"),
         "--nproc", "2", "--modes", "direct,native", "--steps", "8",
         "--warmup", "2", "--layers", "16",
         "--output-dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith('{"metric"')][-1]
    r = json.loads(line)
    assert r["metric"] == "eager_fusion_native_vs_direct"
    # Measured ~3x idle at full scale (~2.5x at this smoke scale); demand
    # a conservative margin so full-suite host load cannot flake the
    # direction of the result.
    assert r["value"] > 1.2, r
    # Fusion must actually have happened (tensors per executed response).
    assert r["native_fusion_ratio"] > 5, r
