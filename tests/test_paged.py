"""Paged KV cache (serving/cache.py PagedSlotCache +
models/transformer.py decode_step_paged / prefill_with_prefix).

The gold check is the same A/B greedy oracle the slot-contiguous
engine ships with, re-proven under paging: whatever the allocation
pattern — page churn, on-demand growth, COW prefix sharing, int8/bf16
storage — the paged engine's greedy output is token-identical to
per-request ``greedy_decode`` AND to the unpaged engine at fixed
config, with the decode executable compiled exactly once.  Page
tables are data, never structure.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving.cache import NULL_PAGE

pytestmark = [pytest.mark.serving, pytest.mark.paged]


def _cfg(**kw):
    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _run_until_done(engine, futs, max_ticks=400):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


def _engine(params, cfg, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 40)
    kw.setdefault("min_prefill_bucket", 4)
    kw.setdefault("page_size", 8)
    return serving.InferenceEngine(params, cfg,
                                   serving.EngineConfig(**kw))


class TestPageAllocator:
    def test_grant_free_refcount_cow(self, model):
        _, cfg = model
        pc = serving.PagedSlotCache(cfg, 2, max_len=32, page_size=8,
                                    n_pages=6)
        s = pc.alloc()
        assert pc.grant(s, 0) == 1  # heapq: lowest page id first
        assert pc.grant(s, 1) == 2
        assert pc.free_pages == 4 and pc.pages_high_water == 2
        # sharing: a raw pin + an attach = refcount 2
        pin = pc.grant_raw(1)
        s2 = pc.alloc()
        pc.attach(s2, pin)
        assert pc.pages_shared == 1
        # COW gives s2 a private copy and drops the share
        new = pc.cow(s2, 0)
        assert new != pin[0] and pc.pages_shared == 0
        assert pc.table[s2, 0] == new
        # freeing returns pages to the heap; the pin survives alone
        pc.free(s)
        pc.free(s2)
        assert pc.free_pages == 6 - 1  # only the pin remains out
        pc.release_raw(pin)
        assert pc.free_pages == 6
        assert pc.pages_high_water == 4  # 2 + pin + cow copy

    def test_out_of_pages_typed(self, model):
        _, cfg = model
        pc = serving.PagedSlotCache(cfg, 2, max_len=32, page_size=8,
                                    n_pages=2)
        s = pc.alloc()
        pc.grant(s, 0), pc.grant(s, 1)
        with pytest.raises(serving.CacheOutOfPagesError):
            pc.grant(s, 2)
        with pytest.raises(serving.CacheOutOfPagesError):
            pc.grant_raw(1)

    def test_default_pool_is_capacity_parity(self, model):
        _, cfg = model
        pc = serving.PagedSlotCache(cfg, 3, max_len=40, page_size=8)
        assert pc.n_pages == 3 * 5  # every slot can still grow to max_len

    def test_slot_free_list_is_fcfs_lowest(self, model):
        # the heapq rewrite keeps SlotCache's allocation order contract
        _, cfg = model
        for cls in (serving.SlotCache, serving.PagedSlotCache):
            slots = cls(cfg, 3, max_len=16)
            assert [slots.alloc() for _ in range(3)] == [0, 1, 2]
            slots.free(1), slots.free(0)
            assert slots.alloc() == 0


class TestPagedOracle:
    """ACCEPTANCE: paged greedy output == unpaged engine == per-request
    greedy_decode at fixed config, decode compiled exactly once across
    churn, growth, and sharing."""

    @pytest.mark.perf
    @pytest.mark.slow
    def test_token_identity_vs_unpaged_engine(self, model):
        params, cfg = model
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (3, 9, 5, 12, 2, 7)]
        steps = 11
        outs = {}
        for paged in (False, True):
            engine = _engine(params, cfg, paged=paged, n_slots=3,
                             max_prefills_per_tick=2, max_queue_depth=8)
            futs = [engine.submit(p, max_new_tokens=steps)
                    for p in prompts]
            _run_until_done(engine, futs)
            outs[paged] = [f.result(timeout=0) for f in futs]
            assert engine.decode_compilations == 1
        assert outs[True] == outs[False]
        for p, out in zip(prompts, outs[True]):
            assert out == _ref_greedy(params, cfg, p, steps)

    def test_growth_crosses_page_boundaries(self, model):
        """A long generation grows page by page (prompt 3 + 30 tokens:
        writes at positions 0..31 span exactly 4 pages at page_size 8
        — the final token is emitted, never written, and the stale
        pipeline tick past it must NOT grant a 5th page) and stays
        oracle-exact."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2)
        fut = engine.submit([5, 9, 2], max_new_tokens=30)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    [5, 9, 2], 30)
        assert engine.decode_compilations == 1
        assert engine.stats()["kv_pages_high_water"] == 4

    @pytest.mark.slow
    def test_page_reuse_no_contamination(self, model):
        """SATELLITE: freed pages re-granted to new requests attend
        only their own tokens — write-before-attend re-proven per PAGE.
        More requests than the pool holds at once, so every later
        request decodes out of recycled pages."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=6,
                         max_queue_depth=16, max_prefills_per_tick=2)
        rng = np.random.default_rng(11)
        cases = [(rng.integers(0, cfg.vocab_size, n).tolist(), s)
                 for n, s in ((4, 6), (8, 3), (2, 9), (6, 5), (3, 7),
                              (9, 4), (5, 8))]
        futs = [engine.submit(p, max_new_tokens=s) for p, s in cases]
        _run_until_done(engine, futs)
        for (p, s), f in zip(cases, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, s)
        assert engine.decode_compilations == 1
        # pages really did recycle: total landed tokens exceed the pool
        assert sum(len(p) + s for p, s in cases) > 6 * 8

    @pytest.mark.slow
    def test_fragmentation_beats_slot_contiguous_ceiling(self, model):
        """SATELLITE: at a fixed HBM budget of 48 cache tokens
        (page_size 8 x 6 pages), the slot-contiguous layout fits
        floor(48 / max_len 40) = ONE worst-case slot; the paged engine
        runs FOUR short requests (each within one page) concurrently
        out of the same bytes."""
        params, cfg = model
        budget_tokens = 48
        ceiling = budget_tokens // 40  # slot-contiguous: 1 request
        engine = _engine(params, cfg, n_slots=4, n_pages=6,
                         max_prefills_per_tick=4, max_queue_depth=8)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, cfg.vocab_size, 3).tolist()
                   for _ in range(4)]
        futs = [engine.submit(p, max_new_tokens=4) for p in prompts]
        peak = 0
        for _ in range(200):
            engine.step()
            peak = max(peak, engine.slots.active_count)
            if all(f.done() for f in futs):
                break
        assert peak > ceiling  # strictly above: 4 > 1
        assert peak == 4
        for p, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, 4)


class TestPrefixSharing:
    @pytest.mark.slow
    def test_shared_prefix_prefilled_once_for_n_requests(self, model):
        """ACCEPTANCE: a registered system prompt is prefilled exactly
        once for N sharers (prefill CALL count asserted), its pages
        refcount-shared, and every output stays oracle-exact — with
        zero decode recompiles across the sharing."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=4, max_queue_depth=8,
                         max_prefills_per_tick=2)
        rng = np.random.default_rng(5)
        prefix = rng.integers(0, cfg.vocab_size, 11).tolist()  # unaligned
        engine.register_prefix(prefix)
        assert engine._prefill_calls == 1
        sufs = [rng.integers(0, cfg.vocab_size, n).tolist()
                for n in (3, 5, 2, 4)]
        futs = [engine.submit(prefix + s, max_new_tokens=7)
                for s in sufs]
        while not all(f.done() for f in futs):
            engine.step()
            # the prefix pages are live-shared while sharers decode
        for s, f in zip(sufs, futs):
            assert f.result(timeout=0) == _ref_greedy(
                params, cfg, prefix + s, 7)
        # 1 prefix prefill + suffix prefills only — NEVER another pass
        # over the prefix tokens (one suffix prefill per admission
        # group; 4 requests / K=2 <= 3 groups under tick timing).
        assert engine._prefill_calls <= 1 + 3
        assert engine.decode_compilations == 1
        assert engine.stats()["requests_completed"] == 4

    def test_prompt_equals_prefix_zero_prefill_admission(self, model):
        """A prompt that IS the prefix admits with NO forward pass at
        all: pages attached, cached first token emitted, decode COWs
        the shared partial page before its first write."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=3, max_queue_depth=8,
                         max_prefills_per_tick=3)
        prefix = [7, 3, 9, 1, 4, 2, 8, 6, 5, 3, 2]  # 11 tokens, unaligned
        engine.register_prefix(prefix)
        calls0 = engine._prefill_calls
        futs = [engine.submit(list(prefix), max_new_tokens=6)
                for _ in range(3)]
        shared_seen = 0
        while not all(f.done() for f in futs):
            engine.step()
            shared_seen = max(shared_seen, engine.slots.pages_shared)
        assert engine._prefill_calls == calls0  # zero admission prefills
        ref = _ref_greedy(params, cfg, prefix, 6)
        for f in futs:
            assert f.result(timeout=0) == ref
        assert shared_seen >= 1  # the full prefix pages were truly shared

    @pytest.mark.slow
    def test_cow_preserves_the_shared_page(self, model):
        """COW semantics: sharers writing into the partial prefix page
        each get a private copy; a LATER sharer still reads the
        original, unclobbered prefix K/V."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8)
        rng = np.random.default_rng(9)
        prefix = rng.integers(0, cfg.vocab_size, 11).tolist()
        engine.register_prefix(prefix)
        # wave 1: two sharers decode INTO their COW'd copies
        w1 = [engine.submit(prefix + rng.integers(0, 64, n).tolist(),
                            max_new_tokens=6) for n in (3, 2)]
        _run_until_done(engine, w1)
        # wave 2: a fresh sharer after wave 1 wrote near the boundary
        suf = rng.integers(0, cfg.vocab_size, 4).tolist()
        f2 = engine.submit(prefix + suf, max_new_tokens=8)
        _run_until_done(engine, [f2])
        assert f2.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + suf, 8)

    @pytest.mark.slow
    def test_sharing_on_vs_off_identical(self, model):
        """ACCEPTANCE: prefix sharing is a pure optimization — the same
        workload with and without the registration is token-identical."""
        params, cfg = model
        rng = np.random.default_rng(13)
        prefix = rng.integers(0, cfg.vocab_size, 8).tolist()  # aligned
        sufs = [rng.integers(0, cfg.vocab_size, n).tolist()
                for n in (4, 2, 6)]
        outs = {}
        for share in (False, True):
            engine = _engine(params, cfg, n_slots=3, max_queue_depth=8,
                             max_prefills_per_tick=2)
            if share:
                engine.register_prefix(prefix)
            futs = [engine.submit(prefix + s, max_new_tokens=9)
                    for s in sufs]
            _run_until_done(engine, futs)
            outs[share] = [f.result(timeout=0) for f in futs]
            assert engine.decode_compilations == 1
        assert outs[True] == outs[False]

    def test_restart_invalidates_and_reprefills_prefix(self, model):
        """A supervised restart replaces the pool: the registry entry
        lazily re-prefills ONCE on next use and sharing keeps working."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8)
        prefix = [1, 2, 3, 4, 5, 6, 7, 8]
        engine.register_prefix(prefix)
        fut = engine.submit(prefix + [9], max_new_tokens=4)
        _run_until_done(engine, [fut])
        calls0 = engine._prefill_calls
        with engine._lock:
            engine._consec_failures = 0
            engine._restart()  # fresh PagedSlotCache, epoch bump
        f2 = engine.submit(prefix + [9, 10], max_new_tokens=4)
        _run_until_done(engine, [f2])
        assert f2.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + [9, 10], 4)
        # exactly one re-registration prefill + one suffix prefill
        assert engine._prefill_calls == calls0 + 2


class TestPrefixRegistryLifecycle:
    def test_terminate_then_unregister_no_refcount_underflow(self, model):
        """REGRESSION: terminate() resets the pool (release_all zeroes
        every refcount) — a later unregister of a pre-terminate prefix
        must be a no-op against the new cache epoch, not a refcount
        underflow."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2)
        prefix = [1, 2, 3, 4, 5, 6, 7, 8]
        engine.register_prefix(prefix)
        engine.terminate("test teardown")
        engine.unregister_prefix(prefix)  # must not raise

    def test_failed_prefix_prefill_releases_its_pages(self, model):
        """REGRESSION: a prefix prefill that dies after its pages were
        pinned must unpin them — otherwise every retry leaks
        pages_for(p0) pages and the pool drains."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=6)
        free0 = engine.slots.free_pages
        boom = RuntimeError("injected prefill failure")
        orig = engine._prefill_fn
        engine._prefill_fn = lambda *a, **k: (_ for _ in ()).throw(boom)
        with pytest.raises(RuntimeError):
            engine.register_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9])
        engine._prefill_fn = orig
        assert engine.slots.free_pages == free0  # nothing pinned/leaked


class TestResumePagedComposition:
    """Restart-resume x paged cache (ISSUE 9 satellites): a resumed
    request re-admits through the SAME paged plumbing — pages
    re-granted, shared prefixes re-attached (suffix prefill, never a
    full pass over the prefix), refcounts balanced — and output stays
    oracle-identical."""

    def _crash_mid_decode(self, engine, fut, inj, min_tokens=2):
        for _ in range(400):
            if len(fut.tokens_so_far()) >= min_tokens or fut.done():
                break
            engine.step()
        assert not fut.done()
        inj.add(serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=inj.visits("decode_tick")))

    def test_resume_attaches_cow_prefix_refcounts_balance(self, model):
        """SATELLITE: resume a request whose slot used a shared COW
        prefix.  The restart re-prefills the PREFIX once (the pool
        died with the crash — the documented lazy re-ensure), but the
        request itself re-admits via attach + SUFFIX prefill, never a
        full pass over prefix + suffix + emitted; refcounts balance
        down to exactly the registry pin; output is oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8,
                         restart_backoff=0.01, faults=inj)
        rng = np.random.default_rng(21)
        prefix = rng.integers(0, cfg.vocab_size, 11).tolist()  # unaligned
        engine.register_prefix(prefix)
        suf = rng.integers(0, cfg.vocab_size, 3).tolist()
        fut = engine.submit(prefix + suf, max_new_tokens=8)
        self._crash_mid_decode(engine, fut, inj)
        calls_at_crash = engine._prefill_calls
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + suf, 8)
        s = engine.stats()
        assert s["requests_resumed"] == 1
        # ONE lazy prefix re-prefill + ONE suffix prefill — a full
        # prefill of prefix+suffix+emitted would also be +2 calls, so
        # pin the shape via the shared-page gauge: the resumed slot
        # ATTACHED the prefix pages (refcount > 1 while decoding).
        assert engine._prefill_calls == calls_at_crash + 2
        assert s["kv_pages_shared"] == 0  # retired: share collapsed
        # refcounts balance to exactly the registry pin
        pin = engine.slots.pages_for(len(prefix))
        assert engine.slots.free_pages == engine.slots.n_pages - pin
        engine.unregister_prefix(prefix)
        assert engine.slots.free_pages == engine.slots.n_pages
        assert s["journal_inflight"] == 0

    def test_resume_shared_pages_live_during_continuation(self, model):
        """The attach is real sharing, not a copy: while the resumed
        request decodes, the prefix pages are referenced by both the
        registry pin and the slot."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8,
                         restart_backoff=0.01, faults=inj)
        prefix = [7, 3, 9, 1, 4, 2, 8, 6, 5, 3, 2]
        engine.register_prefix(prefix)
        fut = engine.submit(prefix + [9, 9], max_new_tokens=9)
        self._crash_mid_decode(engine, fut, inj)
        shared_seen = 0
        for _ in range(400):
            if fut.done():
                break
            engine.step()
            shared_seen = max(shared_seen, engine.slots.pages_shared)
        assert fut.result(timeout=0) == _ref_greedy(
            params, cfg, prefix + [9, 9], 9)
        assert shared_seen >= 1  # resumed slot truly shared the prefix

    def test_resume_prompt_was_prefix_attach_only(self, model):
        """A request admitted attach-only (prompt IS the prefix) whose
        decode COW'd into the shared partial page: after a crash the
        resume prompt is prefix + emitted — the emitted tokens become
        the SUFFIX against the re-pinned prefix, still oracle-exact."""
        params, cfg = model
        inj = serving.FaultInjector()
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8,
                         restart_backoff=0.01, faults=inj)
        prefix = [5, 1, 6, 2, 7, 3, 8, 4, 9, 5, 1]  # 11, unaligned
        engine.register_prefix(prefix)
        fut = engine.submit(list(prefix), max_new_tokens=8)
        self._crash_mid_decode(engine, fut, inj, min_tokens=3)
        _run_until_done(engine, [fut])
        assert fut.result(timeout=0) == _ref_greedy(params, cfg,
                                                    prefix, 8)
        assert engine.stats()["requests_resumed"] == 1
        pin = engine.slots.pages_for(len(prefix))
        assert engine.slots.free_pages == engine.slots.n_pages - pin

    def test_terminate_purges_resumable_journal_entries(self, model):
        """SATELLITE (alongside the PR 7 refcount-underflow
        regression): terminate()/drain of resumable requests purges
        their journal entries — a dead engine leaves no ghost for any
        later lifetime, and the resumed counter stays untouched."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, max_queue_depth=8)
        done = engine.submit([1, 2, 3], max_new_tokens=3)
        _run_until_done(engine, [done])          # retires -> purged
        mid = engine.submit([4, 5], max_new_tokens=20)
        for _ in range(400):
            if len(mid.tokens_so_far()) >= 2:
                break
            engine.step()
        assert len(engine.journal) == 1          # only `mid` lives
        engine.terminate("test teardown")
        with pytest.raises(serving.EngineFailedError):
            mid.result(timeout=0)
        assert len(engine.journal) == 0          # purged, no ghosts
        assert engine.stats()["requests_resumed"] == 0
        assert engine.metrics.resumed.value == 0


class TestQuantizedPages:
    @pytest.mark.slow
    def test_bf16_pages_token_identical_on_bf16_model(self):
        """ACCEPTANCE: with a bf16 model, bf16 page storage is the same
        rounding the slot-contiguous cache applies — paged+bf16 output
        is token-identical to the unpaged engine at fixed config."""
        cfg = _cfg(dtype=jnp.bfloat16)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(17)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (3, 7, 5)]
        outs = {}
        for name, kw in (("unpaged", dict(paged=False)),
                         ("paged_bf16", dict(paged=True,
                                             kv_dtype="bf16"))):
            engine = _engine(params, cfg, n_slots=3,
                             max_prefills_per_tick=2, **kw)
            futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
            _run_until_done(engine, futs)
            outs[name] = [f.result(timeout=0) for f in futs]
        assert outs["paged_bf16"] == outs["unpaged"]

    def test_bf16_pages_halve_cache_bytes_on_f32_model(self, model):
        params, cfg = model
        full = _engine(params, cfg).slots.bytes_per_token
        half = _engine(params, cfg,
                       kv_dtype="bf16").slots.bytes_per_token
        assert half * 2 == full

    def test_int8_roundtrip_error_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
        q, s = T.kv_quantize(x)
        back = T.kv_dequantize(q, s, jnp.float32)
        # symmetric per-vector int8: error <= scale/2 = amax/254
        amax = np.abs(np.asarray(x)).max(-1, keepdims=True)
        assert (np.abs(np.asarray(back) - np.asarray(x))
                <= amax / 254 + 1e-7).all()

    @pytest.mark.slow
    def test_int8_engine_completes_and_matches_oracle(self, model):
        """int8 pages are lossy by design; on this config the per-vector
        scales keep greedy argmax on the oracle path (deterministic —
        verified, not guaranteed at scale), and the byte gauge shows
        the ~4x payload shrink (+ scale overhead).  Slow (PR 17 budget
        pass): ~7 s; the int8 quantize/dequantize units above stay
        tier-1, as does the int8 pool under tp in test_tp_serving."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, kv_dtype="int8",
                         max_queue_depth=8)
        rng = np.random.default_rng(19)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (4, 9)]
        futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
        _run_until_done(engine, futs)
        for p, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, 8)
        assert engine.decode_compilations == 1
        snap = engine.stats()
        f32_bytes = _engine(params, cfg).slots.bytes_per_token
        assert snap["kv_bytes_per_token"] < f32_bytes / 2


class TestBackPressure:
    @pytest.mark.slow
    def test_admission_waits_for_pages_then_completes(self, model):
        """Requests that outsize the free heap WAIT (no rejection, FCFS
        intact) and admit as retirements recycle pages — every future
        still resolves with oracle-exact tokens."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=4, n_pages=4,
                         max_queue_depth=16, max_prefills_per_tick=4)
        rng = np.random.default_rng(23)
        cases = [(rng.integers(0, cfg.vocab_size, 8).tolist(), 7)
                 for _ in range(5)]  # each needs ~2 pages; pool holds 4
        futs = [engine.submit(p, max_new_tokens=s) for p, s in cases]
        engine.step()
        assert engine.scheduler.depth > 0  # someone is waiting on pages
        _run_until_done(engine, futs)
        for (p, s), f in zip(cases, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, s)

    @pytest.mark.slow
    def test_whole_pool_request_admits_eventually(self, model):
        """Slow (PR 17 budget pass): drain-the-pool wait is ~6 s;
        test_decode_growth_exhaustion_preempts_youngest keeps the
        pool-pressure admission path tier-1.

        REGRESSION: a request whose prompt needs every page the pool
        has — so the admission plan's margin heuristic (prompt pages
        + 1) exceeds n_pages outright — must still admit once the pool
        drains, not park the FCFS head (and everyone behind it)
        forever.  The submit-time fit check accepted it; the admission
        budget must not demand more pages than could ever be free."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=4,
                         max_queue_depth=4)
        rng = np.random.default_rng(31)
        big = rng.integers(0, cfg.vocab_size, 26).tolist()  # 4/4 pages
        small = rng.integers(0, cfg.vocab_size, 3).tolist()
        futs = [engine.submit(big, max_new_tokens=6),
                engine.submit(small, max_new_tokens=4)]
        _run_until_done(engine, futs)
        assert futs[0].result(timeout=0) == _ref_greedy(
            params, cfg, big, 6)
        assert futs[1].result(timeout=0) == _ref_greedy(
            params, cfg, small, 4)

    def test_submit_too_big_for_pool_typed_rejection(self, model):
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=2,
                         max_len=40)
        with pytest.raises(serving.CacheOutOfPagesError):
            engine.submit(list(range(20)), max_new_tokens=8)
        assert engine.stats()["requests_rejected"] == 1

    def test_decode_growth_exhaustion_preempts_youngest(self, model):
        """Pool exhaustion mid-decode preempts the YOUNGEST request;
        since PR 14 the victim SUSPENDS through the resume path
        (journal frontier, pages freed, re-admitted once the pool
        clears) instead of failing typed — the older request keeps its
        pages and BOTH finish oracle-exact, the victim byte-identical
        to an uninterrupted run."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=4,
                         max_queue_depth=4, max_prefills_per_tick=2,
                         overlap=False)
        old = engine.submit([3, 4, 5, 6, 7, 8, 9, 1], max_new_tokens=24)
        young = engine.submit([2, 6, 4, 1, 9, 5, 8, 3], max_new_tokens=24)
        _run_until_done(engine, [old, young])
        assert old.result(timeout=0) == _ref_greedy(
            params, cfg, [3, 4, 5, 6, 7, 8, 9, 1], 24)
        assert young.result(timeout=0) == _ref_greedy(
            params, cfg, [2, 6, 4, 1, 9, 5, 8, 3], 24)
        assert engine.stats()["preemptions"] >= 1
        assert engine.slots.active_count == 0  # nothing leaked

    def test_preemption_without_resume_fails_typed(self, model):
        """``resume=False`` keeps the legacy contract: the preempted
        victim resolves with the typed :class:`CacheOutOfPagesError`
        (no journal frontier to suspend onto)."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=4,
                         max_queue_depth=4, max_prefills_per_tick=2,
                         overlap=False, resume=False)
        old = engine.submit([3, 4, 5, 6, 7, 8, 9, 1], max_new_tokens=24)
        young = engine.submit([2, 6, 4, 1, 9, 5, 8, 3], max_new_tokens=24)
        _run_until_done(engine, [old, young])
        assert old.result(timeout=0) == _ref_greedy(
            params, cfg, [3, 4, 5, 6, 7, 8, 9, 1], 24)
        with pytest.raises(serving.CacheOutOfPagesError):
            young.result(timeout=0)
        assert engine.stats()["preemptions"] == 0
        assert engine.slots.active_count == 0  # nothing leaked


class TestPagedObservability:
    def test_page_gauges_in_stats_and_registry(self, model):
        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=8)
        fut = engine.submit([1, 2, 3], max_new_tokens=3)
        _run_until_done(engine, [fut])
        s = engine.stats()
        assert s["kv_pages_total"] == 8
        assert s["kv_pages_free"] == 8  # all recycled after retirement
        assert s["kv_pages_shared"] == 0
        assert s["kv_bytes_per_token"] == engine.slots.bytes_per_token
        assert s["kv_pages_high_water"] >= 1
        assert s["paged"] is True and s["page_size"] == 8
        text = engine.metrics.registry.to_prometheus()
        for fam in ("serving_kv_pages_total", "serving_kv_pages_free",
                    "serving_kv_pages_shared",
                    "serving_kv_bytes_per_token"):
            assert fam in text

    @pytest.mark.perf
    @pytest.mark.slow
    def test_compile_once_and_one_sync_per_tick_across_sharing(self,
                                                               model):
        """PERF GUARD: across admission churn, page growth, prefix
        attach/COW, and preemption-free steady state, the decode
        executable compiles ONCE and the overlapped loop keeps its
        <= 1 host-sync-per-tick contract — page-table maintenance must
        never add a blocking fetch.  Slow (PR 17 budget pass): the
        churn soak is ~8 s; test_sched's chunk-compile-set guard and
        the decode_compilations asserts across the oracle tests keep
        compile-count regressions tier-1."""
        params, cfg = model
        engine = _engine(params, cfg, n_slots=4, max_queue_depth=16,
                         max_prefills_per_tick=2)
        prefix = [9, 8, 7, 6, 5, 4, 3, 2]
        engine.register_prefix(prefix)
        engine.warmup([4, 8])
        warm = engine.decode_compilations
        m0 = engine.stats()
        rng = np.random.default_rng(29)
        futs = [engine.submit(prefix + rng.integers(0, 64, n).tolist(),
                              max_new_tokens=9)
                for n in (2, 4, 3, 2, 5, 1)]
        futs += [engine.submit(rng.integers(0, 64, 5).tolist(),
                               max_new_tokens=9) for _ in range(3)]
        _run_until_done(engine, futs)
        assert engine.decode_compilations == warm == 1
        m1 = engine.stats()
        ticks = m1["decode_ticks"] - m0["decode_ticks"]
        syncs = m1["host_syncs"] - m0["host_syncs"]
        # one deferred fetch per tick + one per admission group
        assert ticks > 0
        assert syncs <= ticks + m1["requests_admitted"]


class TestPagedDecodeKernel:
    @pytest.mark.slow  # ~9 s eager rowwise A/B (PR 19 budget pass,
    # DURATIONS.md); tier-1 siblings: test_growth_crosses_page_boundaries
    # + test_inactive_rows_write_only_the_null_page below
    def test_matches_slot_decode_rowwise(self, model):
        """decode_step_paged row s == decode_step_slots row s for an
        OUT-OF-ORDER page table — the indirection is exact."""
        params, cfg = model
        ps, max_pages, S = 8, 6, 3
        P = 1 + S * max_pages
        pool = serving.init_page_pool(cfg, S, P, ps)
        slots = serving.SlotCache(cfg, S, max_len=48)
        table = np.zeros((S, max_pages), np.int32)
        table[0, :3] = [5, 2, 9]
        table[1, :3] = [1, 7, 3]
        prompts = [[3, 4, 5, 6], [10, 11]]
        for s, p in enumerate(prompts):
            slots.alloc()
            _, pre = T.prefill(params, jnp.asarray([p], jnp.int32),
                               T.init_cache(cfg, 1, len(p)), cfg)
            slots.insert(s, pre)
            pool["pos"] = pool["pos"].at[s].set(len(p))
            for t in range(len(p)):
                pg, off = table[s, t // ps], t % ps
                for n in ("k", "v"):
                    pool[n] = pool[n].at[:, pg, :, off].set(
                        pre[n][:, 0, :, t])
        active = jnp.asarray([True, True, False])
        tokens = jnp.asarray([7, 12, 0], jnp.int32)
        tab = jnp.asarray(table)
        for _ in range(4):
            ls, slots.cache = T.decode_step_slots(
                params, tokens, slots.cache, cfg, active)
            lp, pool = T.decode_step_paged(
                params, tokens, pool, tab, cfg, active)
            np.testing.assert_allclose(np.asarray(lp[:2]),
                                       np.asarray(ls[:2]),
                                       atol=1e-4, rtol=1e-4)
            tokens = jnp.argmax(ls, -1).astype(jnp.int32)
        assert np.asarray(pool["pos"]).tolist()[2] == 0  # inactive froze

    def test_inactive_rows_write_only_the_null_page(self, model):
        """An inactive row's stale scatter must land in page 0 — under
        paging a freed slot's old pages may already belong to someone
        else, so 'harmless overwrite' is not available."""
        params, cfg = model
        ps, max_pages, S = 8, 2, 2
        pool = serving.init_page_pool(cfg, S, 5, ps)
        table = np.zeros((S, max_pages), np.int32)
        table[0, 0] = 3  # the inactive slot STILL points at page 3
        pool["pos"] = jnp.asarray([2, 0], jnp.int32)
        before = np.asarray(pool["k"][:, 3]).copy()
        active = jnp.asarray([False, True])
        _, pool = T.decode_step_paged(
            params, jnp.asarray([9, 9], jnp.int32), pool,
            jnp.asarray(table), cfg, active)
        np.testing.assert_array_equal(np.asarray(pool["k"][:, 3]), before)
        assert np.asarray(pool["k"][:, NULL_PAGE]).any()  # routed to trash

    def test_eager_capacity_guard(self, model):
        params, cfg = model
        pool = serving.init_page_pool(cfg, 2, 5, 8)
        table = np.zeros((2, 2), np.int32)
        pool["pos"] = jnp.asarray([16, 0], jnp.int32)
        with pytest.raises(ValueError, match="capacity"):
            T.decode_step_paged(params, jnp.zeros(2, jnp.int32), pool,
                                jnp.asarray(table), cfg,
                                jnp.asarray([True, False]))


@pytest.mark.paged_kernel
class TestFusedPagedKernel:
    """The fused Pallas flash-decoding kernel (ops/paged_attention.py)
    vs the unfused gather->dequant->attend path.

    TOLERANCE CONTRACT (the satellite audit): int8 dequant is pinned to
    f32 compute in BOTH paths (kv_dequantize and the kernel's fused
    load share DEQUANT_COMPUTE), so f32 and int8 pools agree to f32
    rounding (|dlogits| ~1e-6 at this scale; asserted at atol=1e-4).
    bf16 pools round the attention weights at different points (the
    online-softmax accumulator rescales before the final normalize),
    so logits agree only to bf16 noise (atol=2e-2) — but GREEDY TOKENS
    are identical in every case, which is the landing gate.
    """

    _KV = [None, "bf16", "int8"]

    @pytest.mark.parametrize("kv", _KV)
    def test_kernel_matches_reference_edge_tables(self, model, kv):
        """Unit: Pallas kernel == pure-JAX reference over one layer's
        pool for the edge-case table set — partial last page, a slot at
        exactly table capacity, an inactive (fully masked) slot, and a
        REPEATED page id (the refcount>1 / COW-shared shape: two slots'
        tables referencing the same physical page)."""
        from horovod_tpu.ops import paged_attention as PA

        _, cfg = model
        rng = np.random.RandomState(3)
        S, Hkv, G, Dh, ps, MP = 4, 2, 2, 16, 8, 3
        Pn = 8
        qg = jnp.asarray(rng.randn(S, Hkv, G, Dh), jnp.float32)
        kf = rng.randn(Pn, Hkv, ps, Dh).astype(np.float32)
        vf = rng.randn(Pn, Hkv, ps, Dh).astype(np.float32)
        table = np.asarray(rng.randint(1, Pn, (S, MP)), np.int32)
        table[1] = table[0]           # shared pages, refcount > 1
        limit = jnp.asarray([ps * MP,  # exactly at table capacity
                             5,        # partial last page
                             0,        # inactive: fully masked
                             ps + 3], jnp.int32)
        if kv == "int8":
            kq, ks = T.kv_quantize(jnp.asarray(kf))
            vq, vs = T.kv_quantize(jnp.asarray(vf))
            args = (qg, kq, vq, ks, vs)
        else:
            dt = jnp.bfloat16 if kv == "bf16" else jnp.float32
            args = (qg, jnp.asarray(kf, dt), jnp.asarray(vf, dt),
                    None, None)
        tab = jnp.asarray(table)
        o_r, l_r = PA.paged_attend_reference(*args, tab, limit,
                                             compute_dtype=cfg.dtype)
        o_k, l_k = PA._pallas_paged_attend(*args, tab, limit, cfg.dtype)
        tol = 2e-2 if kv == "bf16" else 1e-4
        np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r),
                                   atol=tol, rtol=tol)
        live = np.asarray(limit) > 0
        np.testing.assert_allclose(np.asarray(l_k)[live],
                                   np.asarray(l_r)[live],
                                   atol=tol, rtol=tol)
        # fully-masked rows: zero output, NEG_INF logsumexp — the
        # combine-neutral element
        assert not np.asarray(o_k)[~live].any()
        assert (np.asarray(l_k)[~live] <= PA.NEG_INF / 2).all()

    def test_dequant_compute_dtype_pinned(self):
        """The satellite audit: the kernel's fused dequant and
        kv_dequantize must round IDENTICALLY — both promote int8
        payload and scale through f32 (DEQUANT_COMPUTE) and cast once,
        even when the target dtype is bf16."""
        from horovod_tpu.ops import paged_attention as PA

        assert PA.DEQUANT_COMPUTE == jnp.float32
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(5, 7, 16), jnp.float32)
        q, s = T.kv_quantize(x)
        for dt in (jnp.float32, jnp.bfloat16):
            np.testing.assert_array_equal(
                np.asarray(PA._dequant(q, s, dt).astype(jnp.float32)),
                np.asarray(T.kv_dequantize(q, s, dt).astype(jnp.float32)))

    @pytest.mark.slow  # ~18 s/variant eager-loop A/B (DURATIONS.md);
    # tier-1 siblings: the kernel-vs-reference edge-table units above
    # (all three pool dtypes) + test_engine_fused_oracle_and_compile_set
    @pytest.mark.parametrize("kv", _KV)
    def test_decode_step_fused_greedy_identical(self, model, kv):
        """decode_step_paged(kernel=True) greedy-matches kernel=False
        over ticks that cross a page boundary, with an inactive row and
        an out-of-order table."""
        params, cfg = model
        rng = np.random.RandomState(1)
        S, Pn, ps, MP = 4, 12, 8, 4
        pool = serving.init_page_pool(cfg, S, Pn, ps, kv_dtype=kv)
        table = jnp.asarray(rng.randint(1, Pn, (S, MP)), jnp.int32)
        active = jnp.asarray([True, True, False, True])
        tu = tk = jnp.asarray(rng.randint(0, 64, (S,)), jnp.int32)
        pool_u, pool_k = dict(pool), dict(pool)
        tol = 2e-2 if kv == "bf16" else 1e-4
        for _ in range(10):  # crosses the ps=8 page boundary
            lu, pool_u = T.decode_step_paged(params, tu, pool_u, table,
                                             cfg, active)
            lk, pool_k = T.decode_step_paged(params, tk, pool_k, table,
                                             cfg, active, kernel=True)
            np.testing.assert_allclose(np.asarray(lk)[np.asarray(active)],
                                       np.asarray(lu)[np.asarray(active)],
                                       atol=tol, rtol=tol)
            au = jnp.argmax(lu, -1).astype(jnp.int32)
            ak = jnp.argmax(lk, -1).astype(jnp.int32)
            assert bool((au[active] == ak[active]).all())
            tu, tk = au, ak
        assert int(pool_k["pos"][2]) == 0  # inactive froze under kernel

    @pytest.mark.slow  # ~18 s eager verify A/B (DURATIONS.md); tier-1
    # sibling: test_engine_speculative_fused_oracle drives the same
    # kernel+LSE-combine verify path through the compiled engine tick
    def test_verify_fused_matches_unfused(self, model):
        """decode_verify_paged(kernel=True): the committed-pages kernel
        + in-window LSE combine produces the same target tokens AND the
        same acceptance as the unfused concat path — including a fresh
        slot at pos 0 (no committed context: the combine's a_c
        underflows to exactly zero)."""
        params, cfg = model
        rng = np.random.RandomState(1)
        S, Pn, ps, MP, W = 4, 12, 8, 4, 4
        table = jnp.asarray(rng.randint(1, Pn, (S, MP)), jnp.int32)
        active = jnp.asarray([True, True, False, True])
        for kv in (None, "int8"):
            pool = serving.init_page_pool(cfg, S, Pn, ps, kv_dtype=kv)
            t = jnp.asarray(rng.randint(0, 64, (S,)), jnp.int32)
            for _ in range(9):
                l, pool = T.decode_step_paged(params, t, pool, table,
                                              cfg, active)
                t = jnp.argmax(l, -1).astype(jnp.int32)
            pool = dict(pool)
            pool["pos"] = pool["pos"].at[3].set(0)  # fresh slot
            win = jnp.asarray(rng.randint(0, 64, (S, W)), jnp.int32)
            tu, mu, accu, _ = T.decode_verify_paged(
                params, win, dict(pool), table, cfg, active)
            tk, mk, acck, _ = T.decode_verify_paged(
                params, win, dict(pool), table, cfg, active, kernel=True)
            a = np.asarray(active)
            np.testing.assert_array_equal(np.asarray(tk)[a],
                                          np.asarray(tu)[a])
            np.testing.assert_array_equal(np.asarray(acck),
                                          np.asarray(accu))
            np.testing.assert_allclose(np.asarray(mk)[a],
                                       np.asarray(mu)[a],
                                       atol=1e-4, rtol=1e-4)

    def test_engine_fused_oracle_and_compile_set(self, model):
        """ACCEPTANCE: a paged_kernel=True engine is token-identical to
        per-request greedy_decode, compiles decode EXACTLY once (the
        fused path adds new executables, not per-tick retraces — the
        compile-set guard re-asserted after a second traffic round),
        and reports paged_kernel_engaged in /stats."""
        from conftest import assert_compile_set

        params, cfg = model
        engine = _engine(params, cfg, paged_kernel=True)
        engine.start()
        try:
            prompts = [[3, 5, 7], [11, 2], [9, 9, 1, 4]]
            futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
            for p, o in zip(prompts, outs):
                assert o == _ref_greedy(params, cfg, p, 8)
            assert engine.stats()["paged_kernel_engaged"] is True
            got = assert_compile_set(engine, decode=1)
            # churn: a new admission in an already-warmed bucket must
            # reuse every executable — same compile set, verbatim
            futs = [engine.submit([1, 2, 3], max_new_tokens=6)]
            assert futs[0].result(timeout=120) == _ref_greedy(
                params, cfg, [1, 2, 3], 6)
            assert_compile_set(engine, decode=1, prefill=got["prefill"],
                               sample=got["sample"])
        finally:
            engine.stop()

    def test_engine_defaults_off_on_cpu_and_disable_works(self, model):
        """paged_kernel=None auto-resolves OFF on a CPU backend (the
        interpreter would own the tick otherwise); False pins it off
        explicitly — both report engaged=False."""
        params, cfg = model
        for flag in (None, False):
            engine = _engine(params, cfg, paged_kernel=flag)
            assert engine.stats()["paged_kernel_engaged"] is False

    @pytest.mark.slow  # ~7 s whole-engine drive (DURATIONS.md); tier-1
    # siblings: test_engine_fused_oracle_and_compile_set (fused engine
    # path) + the COW-shared-rows case in the edge-table units + the
    # TestResumePagedComposition refcount-balance tests
    def test_cow_shared_prefix_fused(self, model):
        """COW-shared prefix pages (refcount > 1) under the fused
        kernel: two requests sharing a registered prefix stream the
        SAME physical pages through the kernel and still match the
        per-request oracle."""
        params, cfg = model
        engine = _engine(params, cfg, paged_kernel=True)
        prefix = [7, 8, 9, 10, 11, 12, 13, 14]  # one full page
        engine.register_prefix(prefix)
        engine.start()
        try:
            suffixes = [[1, 2], [3, 4, 5]]
            futs = [engine.submit(prefix + s, max_new_tokens=6)
                    for s in suffixes]
            outs = [f.result(timeout=120) for f in futs]
            for s, o in zip(suffixes, outs):
                assert o == _ref_greedy(params, cfg, prefix + s, 6)
            assert engine.stats()["prefixes_registered"] == 1
        finally:
            engine.stop()

    @pytest.mark.spec
    @pytest.mark.slow  # ~7 s spec-engine drive (DURATIONS.md); tier-1
    # siblings: test_engine_fused_oracle_and_compile_set (fused engine)
    # + test_speculative's plain spec oracles; the slow verify A/B
    # above covers the kernel+LSE-combine verify math directly
    def test_engine_speculative_fused_oracle(self, model):
        """Spec-decode VERIFY inherits the kernel: a speculative
        paged_kernel=True engine stays token-identical to the plain
        unfused oracle (greedy byte-identity is a property of the
        verify kernel alone)."""
        params, cfg = model
        engine = _engine(params, cfg, paged_kernel=True,
                         speculative=True, spec_k=3)
        engine.start()
        try:
            prompts = [[3, 5, 7], [9, 9, 1, 4]]
            futs = [engine.submit(p, max_new_tokens=8) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
            for p, o in zip(prompts, outs):
                assert o == _ref_greedy(params, cfg, p, 8)
            assert engine.stats()["paged_kernel_engaged"] is True
        finally:
            engine.stop()


class TestPagedHTTP:
    def test_out_of_pages_maps_to_429(self, model):
        from conftest import http_post_json as _post

        params, cfg = model
        engine = _engine(params, cfg, n_slots=2, n_pages=2)
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            code, out = _post(f"http://{host}:{port}/generate",
                              {"tokens": list(range(20)),
                               "max_new_tokens": 8})
        assert (code, out["type"]) == (429, "out_of_pages")
