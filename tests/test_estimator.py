"""Estimator / Store / run-func tests (role of the reference's
test/test_spark.py 23 tests + test_spark_keras/test_spark_torch
estimator tests, minus the Spark session)."""

import os

import numpy as np
import pytest

from horovod_tpu.estimator import (
    EstimatorParams, JaxEstimator, LocalStore, Store, TorchEstimator,
    shard_arrays,
)

pytestmark = pytest.mark.slow  # tier-1 budget: see tests/DURATIONS.md


class TestStore:
    def test_create_picks_local(self, tmp_path):
        s = Store.create(str(tmp_path))
        assert isinstance(s, LocalStore)

    def test_create_hdfs_gated(self):
        with pytest.raises(ImportError, match="pyarrow"):
            Store.create("hdfs://nn:9000/data")

    def test_path_contract(self, tmp_path):
        s = LocalStore(str(tmp_path))
        assert s.get_train_data_path("3").endswith("intermediate_train_data.3")
        assert s.get_checkpoint_path("r1").endswith("runs/r1/checkpoint.pkl")
        assert "runs/r1/logs" in s.get_logs_path("r1")

    def test_array_roundtrip(self, tmp_path):
        s = LocalStore(str(tmp_path))
        arrays = {"x": np.random.randn(10, 3), "y": np.arange(10)}
        s.save_arrays(s.get_train_data_path("0"), arrays)
        out = s.load_arrays(s.get_train_data_path("0"))
        np.testing.assert_array_equal(out["x"], arrays["x"])
        np.testing.assert_array_equal(out["y"], arrays["y"])

    def test_obj_roundtrip(self, tmp_path):
        s = LocalStore(str(tmp_path))
        s.save_obj(s.get_checkpoint_path("r"), {"a": 1})
        assert s.load_obj(s.get_checkpoint_path("r")) == {"a": 1}

    def test_shard_arrays(self):
        shards = shard_arrays({"x": np.arange(10)}, 3)
        assert [len(s["x"]) for s in shards] == [3, 3, 4]
        np.testing.assert_array_equal(
            np.concatenate([s["x"] for s in shards]), np.arange(10))

    def test_shard_length_mismatch(self):
        with pytest.raises(ValueError, match="length"):
            shard_arrays({"x": np.arange(4), "y": np.arange(5)}, 2)


def _run_func_body(tag):
    import os

    return (tag, int(os.environ["HOROVOD_RANK"]))


class TestRunFunc:
    def test_returns_per_rank_results(self):
        from horovod_tpu.runner import run_func

        out = run_func.run(_run_func_body, ("hello",), num_proc=2)
        assert out == [("hello", 0), ("hello", 1)]

    def test_error_propagates(self):
        from horovod_tpu.runner import run_func

        def boom():
            raise RuntimeError("worker exploded")

        with pytest.raises(RuntimeError, match="failed|exploded"):
            run_func.run(boom, num_proc=2)


class TestSparkShim:
    def test_run_falls_back_without_pyspark(self):
        import horovod_tpu.spark as hvd_spark

        out = hvd_spark.run(_run_func_body, ("s",), num_proc=2)
        assert sorted(out) == [("s", 0), ("s", 1)]


def _torch_model_factory():
    import torch

    torch.manual_seed(7)
    return torch.nn.Sequential(
        torch.nn.Linear(4, 16), torch.nn.ReLU(), torch.nn.Linear(16, 1))


def _torch_opt_factory(params):
    import torch

    return torch.optim.SGD(params, lr=0.05)


def _torch_loss(pred, target):
    import torch

    return torch.nn.functional.mse_loss(pred, target)


class TestTorchEstimator:
    def test_fit_predict_end_to_end(self, tmp_path):
        rng = np.random.RandomState(0)
        x = rng.randn(256, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        est = TorchEstimator(
            model_factory=_torch_model_factory,
            optimizer_factory=_torch_opt_factory,
            loss_fn=_torch_loss,
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=8, batch_size=32,
                                   jax_platform="cpu"),
        )
        model = est.fit(x, y)
        assert len(model.history) == 8
        assert model.history[-1] < model.history[0], model.history
        pred = model.predict(x[:8])
        assert pred.shape == (8, 1)
        # trained: much better than predicting zeros
        assert np.mean((pred - y[:8]) ** 2) < np.mean(y[:8] ** 2)


def _jax_init_params(rng):
    import jax

    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (4, 16)) * 0.5,
        "w2": jax.random.normal(k2, (16, 1)) * 0.25,
    }


def _jax_model(params, x):
    import jax.numpy as jnp

    h = jnp.tanh(x @ params["w1"])
    return h @ params["w2"]


def _jax_loss(params, x, y):
    import jax.numpy as jnp

    return jnp.mean((_jax_model(params, x) - y) ** 2)


class TestJaxEstimator:
    def test_fit_predict_end_to_end(self, tmp_path):
        import optax

        rng = np.random.RandomState(1)
        x = rng.randn(256, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        est = JaxEstimator(
            model_fn=_jax_model,
            loss_fn=_jax_loss,
            init_params=_jax_init_params,
            optimizer=optax.adam(1e-2),
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=8, batch_size=32,
                                   jax_platform="cpu"),
        )
        model = est.fit(x, y)
        assert model.history[-1] < model.history[0], model.history
        pred = model.predict(x[:8])
        assert pred.shape == (8, 1)


class TestParquet:
    def test_roundtrip_multidim(self, tmp_path):
        store = LocalStore(str(tmp_path))
        arrays = {
            "img": np.random.RandomState(0).rand(6, 4, 3).astype(np.float32),
            "label": np.arange(6, dtype=np.int64),
        }
        p = str(tmp_path / "data.parquet")
        store.save_parquet(p, arrays)
        back = store.load_parquet(p)
        np.testing.assert_array_equal(back["img"], arrays["img"])
        np.testing.assert_array_equal(back["label"], arrays["label"])

    def test_estimator_trains_from_parquet_shards(self, tmp_path):
        import optax

        rng = np.random.RandomState(1)
        x = rng.randn(128, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        est = JaxEstimator(
            model_fn=_jax_model,
            loss_fn=_jax_loss,
            init_params=_jax_init_params,
            optimizer=optax.adam(1e-2),
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=4, batch_size=16,
                                   storage_format="parquet",
                                   jax_platform="cpu"),
        )
        model = est.fit(x, y)
        assert model.history[-1] < model.history[0]
        # the shards really are parquet (magic), not npz
        with open(LocalStore(str(tmp_path)).get_train_data_path("0"),
                  "rb") as f:
            assert f.read(4) == b"PAR1"

    def test_readable_by_plain_pyarrow(self, tmp_path):
        # interchange: other tools must be able to read what we write
        import pyarrow.parquet as pq

        store = LocalStore(str(tmp_path))
        p = str(tmp_path / "data.parquet")
        store.save_parquet(p, {"a": np.arange(5, dtype=np.float32)})
        t = pq.read_table(p)
        assert t.column_names == ["a"] and len(t) == 5


class TestDataFrameFit:
    def test_df_to_arrays_blocks(self):
        import pandas as pd

        from horovod_tpu.estimator.dataframe import df_to_arrays

        df = pd.DataFrame({
            "f1": [1.0, 2.0, 3.0],
            "vec": [np.full(4, 7.0)] * 3,
            "y": [0.5, 1.5, 2.5],
        })
        x, y = df_to_arrays(df, ["f1", "vec"], ["y"])
        assert x.shape == (3, 5) and y.shape == (3, 1)
        np.testing.assert_allclose(x[:, 0], [1, 2, 3])
        np.testing.assert_allclose(x[:, 1:], 7.0)
        with pytest.raises(ValueError, match="not in DataFrame"):
            df_to_arrays(df, ["nope"], ["y"])

    def test_jax_estimator_fit_df(self, tmp_path):
        import optax
        import pandas as pd

        rng = np.random.RandomState(0)
        xs = rng.randn(128, 4).astype(np.float32)
        df = pd.DataFrame({
            "features": list(xs),
            "target": xs.sum(axis=1),
        })
        est = JaxEstimator(
            model_fn=_jax_model,
            loss_fn=_jax_loss,
            init_params=_jax_init_params,
            optimizer=optax.adam(1e-2),
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=4, batch_size=16,
                                   jax_platform="cpu"),
        )
        model = est.fit_df(df, feature_cols=["features"],
                           label_cols=["target"])
        assert model.history[-1] < model.history[0]


class TestValidationSplit:
    def test_split_semantics(self):
        from horovod_tpu.estimator.estimator import _split_validation

        x = np.arange(100).reshape(100, 1).astype(np.float32)
        y = x.copy()
        xt, yt, xv, yv = _split_validation(x, y, 0.2, seed=3)
        assert len(xv) == 20 and len(xt) == 80
        # deterministic, disjoint, complete
        xt2, _, xv2, _ = _split_validation(x, y, 0.2, seed=3)
        np.testing.assert_array_equal(xt, xt2)
        np.testing.assert_array_equal(xv, xv2)
        assert not set(xv.ravel()) & set(xt.ravel())
        assert _split_validation(x, y, None, 0)[2] is None
        with pytest.raises(ValueError):
            _split_validation(x, y, 1.5, 0)

    def test_tiny_validation_fraction_rejected(self, tmp_path):
        # a split leaving fewer val rows than workers would give some
        # rank an EMPTY shard -> NaN poisoning the epoch reduction
        from horovod_tpu.estimator.estimator import _stage_data

        store = LocalStore(str(tmp_path))
        x = np.zeros((100, 2), np.float32)
        y = np.zeros((100, 1), np.float32)
        with pytest.raises(ValueError, match="empty validation shard"):
            _stage_data(store, x, y,
                        EstimatorParams(num_proc=4, validation=0.01))

    def test_jax_estimator_reports_val_history(self, tmp_path):
        import optax

        rng = np.random.RandomState(1)
        x = rng.randn(256, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        est = JaxEstimator(
            model_fn=_jax_model,
            loss_fn=_jax_loss,
            init_params=_jax_init_params,
            optimizer=optax.adam(1e-2),
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=6, batch_size=16,
                                   validation=0.25, jax_platform="cpu"),
        )
        model = est.fit(x, y)
        assert len(model.val_history) == 6
        assert model.val_history[-1] < model.val_history[0], model.val_history


class TestKerasEstimator:
    def test_fit_predict_end_to_end(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        from horovod_tpu.estimator import KerasEstimator

        model = tf.keras.Sequential([
            tf.keras.layers.Input(shape=(4,)),
            tf.keras.layers.Dense(8, activation="tanh"),
            tf.keras.layers.Dense(1),
        ])
        rng = np.random.RandomState(2)
        x = rng.randn(128, 4).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        est = KerasEstimator(
            model=model,
            optimizer=tf.keras.optimizers.SGD(0.02),
            loss="mse",
            store=LocalStore(str(tmp_path)),
            params=EstimatorParams(num_proc=2, epochs=3, batch_size=16,
                                   jax_platform="cpu"),
        )
        trained = est.fit(x, y)
        losses = trained.history["loss"]
        assert losses[-1] < losses[0], losses
        pred = trained.predict(x[:8])
        assert pred.shape == (8, 1)
        # transformer is self-contained: rebuilds from json+weights
        rebuilt = trained.keras_model()
        assert len(rebuilt.get_weights()) == len(trained.weights)
