"""Speculative decoding (EngineConfig.speculative) acceptance suite.

THE correctness bar (ISSUE 11): with speculation on — either draft
source — every request's output is BYTE-IDENTICAL to non-speculative
greedy decode (and to per-request ``greedy_decode``), across staggered
admission, EOS inside an accepted run, cancellation, restart-resume
mid-speculation, and paged COW-prefix sharing, while the decode
executable compiles exactly ONCE no matter how per-slot acceptance
lengths vary (acceptance is data, not structure).

Layers:

* kernel unit — ``decode_verify_paged`` against sequential
  ``decode_step_paged`` (acceptance math, NULL-routing of rejected
  drafts, storage round-trip), ``ngram_propose``;
* ``_retire_pending`` multi-token emission as a STANDALONE unit
  (fabricated pending dicts, no device decode): 0 / 1 / k < K / K+1
  tokens per slot, EOS inside the run, stale-slot identity drop;
* whole-engine A/B oracles.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving.engine import _SlotState
from horovod_tpu.serving.faults import FaultInjector, FaultSpec
from horovod_tpu.serving.scheduler import Request

pytestmark = [pytest.mark.serving, pytest.mark.spec]

SPEC_K = 3


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


def _draft_cfg():
    # The shallow draft: half the layers, same tokenizer/vocab.
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


@pytest.fixture(scope="module")
def draft_model():
    dcfg = _draft_cfg()
    return T.init_params(jax.random.PRNGKey(7), dcfg), dcfg


def _ref(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _engine(model, *, speculative=True, draft=None, **kw):
    params, cfg = model
    defaults = dict(n_slots=4, max_len=40, min_prefill_bucket=4,
                    max_prefills_per_tick=2, max_queue_depth=16,
                    restart_backoff=0.01, restart_backoff_max=0.05,
                    speculative=speculative, spec_k=SPEC_K)
    defaults.update(kw)
    dp, dc = draft if draft is not None else (None, None)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults),
        draft_params=dp, draft_cfg=dc)


def _drive(engine, futs, max_ticks=500):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


# --- kernel unit --------------------------------------------------------------


class TestVerifyKernel:
    """decode_verify_paged against sequential decode_step_paged."""

    def _prefilled(self, model, prompt, n_slots=2, page_size=8,
                   pages_per_slot=2):
        params, cfg = model
        pc = serving.cache.PagedSlotCache(cfg, n_slots, 32,
                                          page_size=page_size)
        slots = [pc.alloc() for _ in range(n_slots)]
        for s in slots:
            for idx in range(pages_per_slot):
                pc.grant(s, idx)
        cache = T.init_cache(cfg, n_slots, 8)
        logits, pre = T.prefill(
            params, jnp.asarray([prompt] * n_slots, jnp.int32), cache,
            cfg, true_len=jnp.asarray([len(prompt)] * n_slots))
        pc.land(slots, pre, [len(prompt)] * n_slots, start=0)
        first = int(jnp.argmax(logits[0]))
        return pc, first

    def _sequential(self, model, pool, table, first, n, active):
        params, cfg = model
        cur = jnp.asarray([first] * int(active.shape[0]), jnp.int32)
        out = []
        for _ in range(n):
            lg, pool = T.decode_step_paged(params, cur, pool, table,
                                           cfg, active)
            cur = jnp.argmax(lg, -1).astype(jnp.int32)
            out.append(np.asarray(cur))
        return np.stack(out), pool, cur

    def test_perfect_drafts_accept_all(self, model):
        params, cfg = model
        pc, first = self._prefilled(model, [3, 4, 5, 6, 7])
        table = jnp.asarray(pc.table)
        active = jnp.asarray([True, True])
        seq, _, _ = self._sequential(model, pc.cache, table, first, 4,
                                     active)
        window = jnp.concatenate(
            [jnp.full((2, 1), first, jnp.int32),
             jnp.asarray(seq[:3].T, jnp.int32)], axis=1)
        t, mx, acc, pool = T.decode_verify_paged(
            params, window, pc.cache, table, cfg, active)
        assert np.asarray(acc).tolist() == [3, 3]
        assert np.array_equal(np.asarray(t).T, seq)
        assert np.asarray(pool["pos"]).tolist() == [9, 9]
        assert np.isfinite(np.asarray(mx)).all()

    def test_rejected_drafts_accept_none_and_never_contaminate(
            self, model):
        """Garbage drafts: acceptance 0, position 0's token is STILL
        the greedy token, the pool's committed pages are bit-identical
        to a plain one-token tick's (rejected drafts NULL-routed), and
        continuing from the verified pool matches the sequential
        stream exactly."""
        params, cfg = model
        pc, first = self._prefilled(model, [3, 4, 5, 6, 7])
        table = jnp.asarray(pc.table)
        active = jnp.asarray([True, True])
        seq, seq_pool, seq_cur = self._sequential(
            model, pc.cache, table, first, 1, active)
        window = jnp.asarray([[first, 9, 9, 9]] * 2, jnp.int32)
        before_k = np.asarray(pc.cache["k"])
        t, _, acc, pool = T.decode_verify_paged(
            params, window, pc.cache, table, cfg, active)
        assert np.asarray(acc).tolist() == [0, 0]
        assert np.array_equal(np.asarray(t)[:, 0], seq[0])
        # NULL routing, EXACTLY: with every draft rejected, only the
        # committed token's position (pos=5, page offset 5) may change
        # in each slot's own page — offsets 6 and 7, where the
        # rejected drafts WOULD have landed, are bit-identical to the
        # pre-verify pool.  The junk went to physical page 0 only.
        after_k = np.asarray(pool["k"])
        for s in (0, 1):
            pg = int(np.asarray(table)[s, 0])
            np.testing.assert_array_equal(after_k[:, pg, :, 6:],
                                          before_k[:, pg, :, 6:])
            assert (after_k[:, pg, :, 5] != before_k[:, pg, :, 5]).any()
        # And the accepted write agrees with the sequential tick's to
        # reduction-order precision (the verify's W-wide softmax may
        # associate sums differently — ULP noise, not contamination;
        # TOKEN identity is exact, proven by the engine-level A/Bs).
        np.testing.assert_allclose(
            np.asarray(pool["k"][:, 1:]), np.asarray(seq_pool["k"][:, 1:]),
            atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pool["v"][:, 1:]), np.asarray(seq_pool["v"][:, 1:]),
            atol=1e-5, rtol=1e-5)
        assert np.asarray(pool["pos"]).tolist() == \
            np.asarray(seq_pool["pos"]).tolist()
        # Continue both paths one tick: identical next tokens.
        lg_a, _ = T.decode_step_paged(
            params, t[jnp.arange(2), acc], pool, table, cfg, active)
        lg_b, _ = T.decode_step_paged(
            params, seq_cur, seq_pool, table, cfg, active)
        assert np.array_equal(np.asarray(jnp.argmax(lg_a, -1)),
                              np.asarray(jnp.argmax(lg_b, -1)))

    def test_partial_acceptance_continues_identically(self, model):
        params, cfg = model
        pc, first = self._prefilled(model, [3, 4, 5, 6, 7])
        table = jnp.asarray(pc.table)
        active = jnp.asarray([True, True])
        seq, _, _ = self._sequential(model, pc.cache, table, first, 3,
                                     active)
        window = jnp.concatenate(
            [jnp.full((2, 1), first, jnp.int32),
             jnp.asarray(seq[:1].T, jnp.int32),
             jnp.full((2, 2), 9, jnp.int32)], axis=1)
        t, _, acc, pool = T.decode_verify_paged(
            params, window, pc.cache, table, cfg, active)
        assert np.asarray(acc).tolist() == [1, 1]
        bonus = np.asarray(t[jnp.arange(2), acc])
        assert np.array_equal(bonus, seq[1])
        lg, _ = T.decode_step_paged(
            params, jnp.asarray(bonus), pool, table, cfg, active)
        assert np.array_equal(np.asarray(jnp.argmax(lg, -1)), seq[2])

    def test_spec_on_mask_forces_plain_greedy(self, model):
        """spec_on=False is the per-request opt-out: acceptance forced
        to 0 as data, one greedy token per tick, same executable."""
        params, cfg = model
        pc, first = self._prefilled(model, [3, 4, 5, 6, 7])
        table = jnp.asarray(pc.table)
        active = jnp.asarray([True, True])
        seq, _, _ = self._sequential(model, pc.cache, table, first, 4,
                                     active)
        window = jnp.concatenate(
            [jnp.full((2, 1), first, jnp.int32),
             jnp.asarray(seq[:3].T, jnp.int32)], axis=1)  # perfect
        t, _, acc, pool = T.decode_verify_paged(
            params, window, pc.cache, table, cfg, active,
            jnp.asarray([False, True]))
        assert np.asarray(acc).tolist() == [0, 3]
        assert np.asarray(pool["pos"]).tolist() == [6, 9]

    def test_inactive_rows_untouched(self, model):
        params, cfg = model
        pc, first = self._prefilled(model, [3, 4, 5, 6, 7])
        table = jnp.asarray(pc.table)
        active = jnp.asarray([True, False])
        window = jnp.asarray([[first, 9, 9, 9]] * 2, jnp.int32)
        before = np.asarray(pc.cache["k"])
        t, _, acc, pool = T.decode_verify_paged(
            params, window, pc.cache, table, cfg, active)
        assert np.asarray(acc)[1] == 0
        assert np.asarray(pool["pos"]).tolist() == [6, 5]  # row 1 frozen
        # Row 1's pages (its table maps pages for slot 1) unchanged.
        for pg in pc.table[1]:
            if pg:
                np.testing.assert_array_equal(
                    np.asarray(pool["k"][:, pg]), before[:, pg])

    def test_ngram_propose(self):
        hist = jnp.asarray([[1, 2, 3, 1, 2, 0, 0, 0],
                            [5, 5, 5, 5, 5, 0, 0, 0],
                            [1, 2, 3, 4, 5, 0, 0, 0]], jnp.int32)
        pos = jnp.asarray([4, 4, 4], jnp.int32)
        d = np.asarray(T.ngram_propose(hist, pos, 3))
        # Row 0: final bigram (1,2) seen at 0 -> copy [3, 1, 2].
        assert d[0].tolist() == [3, 1, 2]
        # Row 1: (5,5) most recent at 2 -> copy window runs past the
        # committed region, whose positions fall back to the last
        # token: all 5s (the pure-repeat case must draft the repeat).
        assert d[1].tolist() == [5, 5, 5]
        # Row 2: no earlier (4,5) -> fallback repeats the last token.
        assert d[2].tolist() == [5, 5, 5]


# --- _retire_pending multi-token emission, standalone -------------------------


class TestRetirePendingMultiToken:
    """The deferred-fetch boundary's 0..K+1-tokens-per-slot contract,
    driven with FABRICATED pending dicts — no device decode, no draft
    source: exactly the host-side emission rules in isolation."""

    def _engine_with_slot(self, model, *, max_new=10, eos=None,
                          prompt=(1, 2)):
        # resume=False: no journal, so fabricated requests need no
        # journal entries.
        eng = _engine(model, speculative=True, resume=False)
        fut = serving.GenerationFuture()
        req = Request(prompt=list(prompt), max_new_tokens=max_new,
                      future=fut, eos_id=eos)
        slot = eng.slots.alloc()
        eng._states[slot] = _SlotState(request=req, last_token=5,
                                       n_generated=1)
        eng._page_pos[slot] = len(prompt)
        return eng, slot, req, fut

    def _pending(self, eng, slot, req, row, acc):
        S = eng.engine_cfg.n_slots
        W = eng.engine_cfg.spec_k + 1
        nxt = np.zeros((S, W), np.int32)
        nxt[slot] = row
        active = np.zeros(S, bool)
        active[slot] = True
        accs = np.zeros(S, np.int32)
        accs[slot] = acc
        reqs = [None] * S
        reqs[slot] = req
        return {"nxt": nxt, "mx": np.ones((S, W), np.float32),
                "acc": accs, "active": active, "reqs": reqs,
                "kind": None, "dispatched_at": time.monotonic(),
                "spec": np.ones(S, bool)}

    @pytest.mark.parametrize("acc,want", [(0, 1), (1, 2), (2, 3),
                                          (SPEC_K, SPEC_K + 1)])
    def test_emits_acc_plus_one(self, model, acc, want):
        eng, slot, req, fut = self._engine_with_slot(model)
        eng._retire_pending(self._pending(eng, slot, req,
                                          [11, 12, 13, 14], acc))
        assert fut.tokens_so_far() == [11, 12, 13, 14][:want]
        assert eng._states[slot] is not None  # still running
        assert eng._page_pos[slot] == 2 + acc + 1  # device-pos mirror

    def test_zero_tokens_on_stale_identity(self, model):
        """A slot retired and REUSED between dispatch and fetch emits
        nothing from the stale tick — no token leaks into the new
        tenant."""
        eng, slot, req, fut = self._engine_with_slot(model)
        other = Request(prompt=[9], max_new_tokens=5,
                        future=serving.GenerationFuture())
        p = self._pending(eng, slot, req, [11, 12, 13, 14], SPEC_K)
        # The slot now belongs to someone else (re-admission landed).
        eng._states[slot] = _SlotState(request=other, last_token=1,
                                       n_generated=0)
        eng._retire_pending(p)
        assert fut.tokens_so_far() == []
        assert other.future.tokens_so_far() == []

    def test_eos_inside_run_drops_tail(self, model):
        eng, slot, req, fut = self._engine_with_slot(model, eos=12)
        eng._retire_pending(self._pending(eng, slot, req,
                                          [11, 12, 13, 14], SPEC_K))
        assert fut.tokens_so_far() == [11, 12]  # tail dropped
        assert fut.finish_reason == "eos"
        assert eng._states[slot] is None  # retired, slot reclaimed
        assert eng.slots.free_count == eng.engine_cfg.n_slots

    def test_length_inside_run_drops_tail(self, model):
        # n_generated=1 already; max_new=3 -> only 2 more tokens fit.
        eng, slot, req, fut = self._engine_with_slot(model, max_new=3)
        eng._retire_pending(self._pending(eng, slot, req,
                                          [11, 12, 13, 14], SPEC_K))
        assert fut.tokens_so_far() == [11, 12]
        assert fut.finish_reason == "length"

    def test_plain_single_token_path_unchanged(self, model):
        """Without "acc" the pending dict is the PR 4 contract —
        one token per slot."""
        eng, slot, req, fut = self._engine_with_slot(model)
        S = eng.engine_cfg.n_slots
        nxt = np.zeros(S, np.int32)
        nxt[slot] = 21
        active = np.zeros(S, bool)
        active[slot] = True
        reqs = [None] * S
        reqs[slot] = req
        eng._retire_pending({
            "nxt": nxt, "mx": np.ones(S, np.float32), "active": active,
            "reqs": reqs, "kind": None,
            "dispatched_at": time.monotonic()})
        assert fut.tokens_so_far() == [21]


# --- whole-engine oracle A/Bs -------------------------------------------------


# Same staggered mixed workload as tests/test_overlap.py: two prompt
# buckets, unequal completion lengths, slot reuse, more requests than
# slots, one EOS case resolved against the oracle.
_CASES = [
    ([3, 4, 5, 6], 9, None),
    ([10, 11], 5, None),
    ([7, 8, 9, 1, 2, 3, 4, 5, 6], 7, None),
    ([12, 13, 14], 11, None),
    ([5, 6], 4, None),
    ([20, 21, 22], 12, "eos"),
]


class TestSpeculativeOracle:
    def _resolved_cases(self, model):
        params, cfg = model
        cases = []
        for prompt, steps, kind in _CASES:
            ref = _ref(params, cfg, prompt, steps)
            eos = ref[2] if kind == "eos" else None
            cases.append((prompt, steps, eos, ref))
        return cases

    def _run_staggered(self, engine, cases):
        futs = []
        for prompt, steps, eos, _ in cases:
            futs.append(engine.submit(prompt, max_new_tokens=steps,
                                      eos_id=eos))
            engine.step()
            engine.step()
        _drive(engine, futs)
        return [(f.result(timeout=0), f.finish_reason) for f in futs]

    def _assert_oracle(self, cases, outs):
        for (prompt, steps, eos, ref), (toks, reason) in zip(cases, outs):
            if eos is None:
                assert toks == ref
                assert reason == "length"
            else:
                assert toks == ref[:ref.index(eos) + 1]
                assert reason == "eos"

    @pytest.mark.slow
    def test_ab_identity_staggered_ngram(self, model):
        """ACCEPTANCE: the staggered workload through an n-gram
        speculative engine is byte-identical to the non-speculative
        engine and to greedy_decode — and the decode compile count is
        CONSTANT across varying per-slot acceptance: at most the two
        executables the engine owns (draft/verify + the plain
        fallback adaptive disabling dispatches), with ZERO growth when
        the whole varying-acceptance workload runs again."""
        cases = self._resolved_cases(model)
        eng = _engine(model, speculative=True)
        outs = self._run_staggered(eng, cases)
        c1 = eng.decode_compilations
        assert c1 <= 2
        outs2 = self._run_staggered(eng, cases)
        assert eng.decode_compilations == c1  # acceptance is data
        assert outs2 == outs
        base = self._run_staggered(_engine(model, speculative=False),
                                   cases)
        assert outs == base
        self._assert_oracle(cases, outs)
        snap = eng.metrics.tokens_per_tick.snapshot()
        assert snap["count"] > 0

    @pytest.mark.slow
    def test_ab_identity_staggered_model_draft(self, model, draft_model):
        cases = self._resolved_cases(model)
        eng = _engine(model, speculative=True, draft=draft_model)
        outs = self._run_staggered(eng, cases)
        c1 = eng.decode_compilations
        assert c1 <= 2
        assert self._run_staggered(eng, cases) == outs
        assert eng.decode_compilations == c1
        self._assert_oracle(cases, outs)

    @pytest.mark.slow
    def test_ab_identity_sync_mode(self, model):
        """speculative + overlap=False (the synchronous tick) — same
        oracle."""
        cases = self._resolved_cases(model)
        outs = self._run_staggered(
            _engine(model, speculative=True, overlap=False), cases)
        self._assert_oracle(cases, outs)

    def test_perfect_draft_eos_inside_accepted_run(self, model):
        """Draft = the target itself -> every draft accepted, so the
        EOS genuinely lands INSIDE an accepted run and the tail must
        be dropped (plus the tokens/tick histogram proves multi-token
        ticks actually happened)."""
        params, cfg = model
        full = _ref(params, cfg, [3, 4, 5, 6], 9)
        eos = full[2]
        eng = _engine(model, speculative=True, draft=(params, cfg))
        f = eng.submit([3, 4, 5, 6], max_new_tokens=9, eos_id=eos)
        _drive(eng, [f])
        assert f.result(timeout=0) == full[:3]
        assert f.finish_reason == "eos"
        assert eng.metrics.spec_accepted.value > 0

    def test_perfect_draft_multiplies_tokens_per_tick(self, model):
        params, cfg = model
        eng = _engine(model, speculative=True, draft=(params, cfg))
        f = eng.submit([3, 4, 5, 6], max_new_tokens=12)
        _drive(eng, [f])
        assert f.result(timeout=0) == _ref(params, cfg, [3, 4, 5, 6], 12)
        # A perfect draft accepts everything: mean tokens/tick well
        # above 1 (the speculative multiplier), acceptance ratio 1.
        assert eng.metrics.spec_drafted.value == \
            eng.metrics.spec_accepted.value
        assert eng.metrics.tokens_per_tick.snapshot()["mean"] > 1.5

    def test_cancellation_mid_speculation(self, model):
        params, cfg = model
        eng = _engine(model, speculative=True)
        f1 = eng.submit([3, 4, 5, 6], max_new_tokens=30)
        f2 = eng.submit([10, 11], max_new_tokens=6)
        eng.step()
        eng.step()
        f1.cancel()
        _drive(eng, [f1, f2])
        assert f1.finish_reason == "cancelled"
        got = f1.tokens_so_far()
        assert got == _ref(params, cfg, [3, 4, 5, 6], 30)[:len(got)]
        assert f2.result(timeout=0) == _ref(params, cfg, [10, 11], 6)

    def test_per_request_opt_out(self, model):
        params, cfg = model
        eng = _engine(model, speculative=True, draft=(params, cfg))
        f1 = eng.submit([3, 4, 5, 6], max_new_tokens=9,
                        speculative=False)
        f2 = eng.submit([10, 11], max_new_tokens=5)
        _drive(eng, [f1, f2])
        assert f1.result(timeout=0) == _ref(params, cfg, [3, 4, 5, 6], 9)
        assert f2.result(timeout=0) == _ref(params, cfg, [10, 11], 5)
        # Opt-out is data: at most the engine's two executables (the
        # opted-out request alone in the pool dispatches the plain
        # fallback), never a per-pattern recompile.
        assert eng.decode_compilations <= 2

    def test_adaptive_disable_and_probe_cycle(self, model):
        """Losing speculation is BOUNDED: the random model's stream
        gives the n-gram draft nothing to agree with, so adaptive
        control disables the slot after the evaluation window (plain
        one-token ticks thereafter), probes re-enable it periodically,
        and the output stays byte-identical through every
        disable/probe/re-disable transition."""
        params, cfg = model
        eng = _engine(model, speculative=True, spec_probe_period=8,
                      spec_window=2)
        f = eng.submit([3, 4, 5, 6], max_new_tokens=30)
        saw_disabled = False
        for _ in range(500):
            if f.done():
                break
            eng.step()
            saw_disabled |= not eng._spec_live.all()
        assert f.done()
        assert f.result(timeout=0) == _ref(params, cfg, [3, 4, 5, 6], 30)
        assert saw_disabled
        assert eng.decode_compilations <= 2

    @pytest.mark.chaos
    @pytest.mark.parametrize("skip", [
        1,
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(3, marks=pytest.mark.slow),
    ])
    def test_restart_resume_mid_speculation(self, model, skip):
        """Crash the decode tick at several depths (measured in
        SPECULATIVE ticks, each worth up to K+1 tokens): resumed
        output stays byte-identical, futures stay live.  Depth 1 is
        the tier-1 sibling; the deeper crashes are slow-marked."""
        params, cfg = model
        want = [_ref(params, cfg, [3, 4, 5, 6], 9),
                _ref(params, cfg, [7, 8, 9, 1, 2, 3, 4, 5, 6], 7)]
        inj = FaultInjector([FaultSpec(site="decode_tick",
                                       kind="raise", skip=skip)])
        eng = _engine(model, speculative=True, faults=inj)
        futs = [eng.submit([3, 4, 5, 6], max_new_tokens=9),
                eng.submit([7, 8, 9, 1, 2, 3, 4, 5, 6],
                           max_new_tokens=7)]
        _drive(eng, futs)
        assert [f.result(timeout=0) for f in futs] == want
        assert inj.fired
        assert eng.metrics.resumed.value > 0

    @pytest.mark.paged
    @pytest.mark.slow
    def test_cow_prefix_sharing_under_speculation(self, model):
        """Registered-prefix sharers (one prefill, refcounted pages,
        COW growth) decode speculatively and stay oracle-identical —
        including the attach-only admission (prompt == prefix).
        Slow (PR 17 budget pass): ~10 s; the plain spec oracle tests
        here and the COW ladder in test_paged keep each axis
        tier-1."""
        params, cfg = model
        eng = _engine(model, speculative=True)
        pre = [9, 9, 9, 9, 9, 1, 2]
        eng.register_prefix(pre)
        futs = [eng.submit(pre + [k], max_new_tokens=8)
                for k in (3, 4, 5)]
        futs.append(eng.submit(pre, max_new_tokens=6))
        _drive(eng, futs)
        for fu, k in zip(futs[:3], (3, 4, 5)):
            assert fu.result(timeout=0) == _ref(params, cfg, pre + [k], 8)
        assert futs[3].result(timeout=0) == _ref(params, cfg, pre, 6)
        assert eng._prefill_calls <= 3  # prefix once + <=2 group fills

    @pytest.mark.slow
    @pytest.mark.paged
    @pytest.mark.parametrize("kvd", ["bf16", "int8"])
    def test_quantized_pages_oracle(self, model, kvd):
        """Speculative output on bf16/int8 pages equals the
        NON-speculative engine on the same storage (the verify kernel
        round-trips window K/V through the storage dtype, so the two
        paths see identical caches)."""
        outs = {}
        for spec in (True, False):
            eng = _engine(model, speculative=spec, kv_dtype=kvd)
            futs = [eng.submit([3, 4, 5, 6], max_new_tokens=9),
                    eng.submit([10, 11], max_new_tokens=6)]
            _drive(eng, futs)
            outs[spec] = [f.result(timeout=0) for f in futs]
        assert outs[True] == outs[False]

    def test_speculative_requires_paged(self, model):
        with pytest.raises(ValueError, match="paged"):
            _engine(model, speculative=True, paged=False)

    def test_model_draft_requires_shared_vocab(self, model):
        params, cfg = model
        bad = _draft_cfg()
        bad = type(bad)(**{**bad.__dict__, "vocab_size": 32})
        with pytest.raises(ValueError, match="tokenizer|vocab"):
            _engine(model, speculative=True,
                    draft=(T.init_params(jax.random.PRNGKey(1), bad),
                           bad))

    def test_stats_and_metrics_surface(self, model):
        eng = _engine(model, speculative=True)
        f = eng.submit([3, 4, 5, 6], max_new_tokens=6)
        _drive(eng, [f])
        st = eng.stats()
        assert st["speculative"] is True
        assert st["spec_k"] == SPEC_K
        assert st["spec_draft"] == "ngram"
        assert st["spec_drafted_tokens"] >= st["spec_accepted_tokens"]
        assert st["tokens_per_tick"]["count"] > 0
