"""Elastic recovery end-to-end: kill one rank of a 3-process job, relaunch
with the 2 survivors, resume from the committed State.

Reference behavior bar (VERDICT r1 #9): ``gloo_run.py:162-259`` kill-all
on any-rank failure + the §5.3/5.4 recovery conventions (rank-0 commit,
restore-then-broadcast, re-init with surviving hosts).  Membership change
on TPU means a fresh mesh: the relaunch IS the recovery mechanism, and
:class:`horovod_tpu.elastic.State` guarantees the survivors resume from
one consistent (step, params) point.
"""

import json
import os
import sys

import pytest

from horovod_tpu import native
from horovod_tpu.runner import launch
from horovod_tpu.runner.hosts import HostSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, phase, nproc, crash_rank=None):
    out = tmp_path / f"out.{phase}"
    results = tmp_path / f"results.{phase}"
    results.mkdir()
    env = {
        "PATH": os.environ.get("PATH", ""),
        "REPO": REPO,
        "PALLAS_AXON_POOL_IPS": "",  # keep subprocesses off the TPU
        "HOROVOD_NUM_PROC": str(nproc),
        "HOROVOD_JAX_PORT": str(_free_port()),
        "HOROVOD_NATIVE_PORT": str(_free_port()),
        "HOROVOD_CYCLE_TIME": "1",
        "ELASTIC_CKPT": str(tmp_path / "state.ckpt"),
        "ELASTIC_RESULTS": str(results),
    }
    if crash_rank is not None:
        env["ELASTIC_CRASH_RANK"] = str(crash_rank)
    rc = launch.launch_job(
        [sys.executable, WORKER],
        [HostSpec("localhost", 1)] * nproc,
        env=env,
        output_filename=str(out),
    )
    return rc, out, results


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
class TestElasticRecovery:
    def test_crash_relaunch_resume(self, tmp_path):
        # Phase 1: 3 ranks, rank 2 dies at step 7 (after the step-5
        # commit).  The launcher must kill the survivors — nonzero exit,
        # no final results, but a checkpoint at step 5.
        rc, out, results = _launch(tmp_path, 1, nproc=3, crash_rank=2)
        assert rc != 0, "crash must fail the whole job (kill-all)"
        assert not list(results.iterdir()), "no rank may have finished"
        assert (tmp_path / "state.ckpt").exists()
        crash_log = (out / "rank.2.stdout").read_text()
        assert "ELASTIC-WORKER-CRASH rank=2 step=7" in crash_log

        # Phase 2: relaunch with the 2 survivors; they restore step 5 and
        # run to completion with consistent state.
        rc, out, results = _launch(tmp_path, 2, nproc=2)
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / "rank.1.stderr").read_text()
        finals = sorted(results.iterdir())
        assert len(finals) == 2
        records = [json.loads(p.read_text()) for p in finals]
        assert all(r["resumed_from"] == 5 for r in records), records
        assert all(r["step"] == 10 for r in records), records
        assert all(r["size"] == 2 for r in records), records
        # consistent state across the survivors
        assert records[0]["checksum"] == pytest.approx(
            records[1]["checksum"]), records

    def test_fresh_run_completes_without_checkpoint(self, tmp_path):
        rc, out, results = _launch(tmp_path, 1, nproc=2)
        assert rc == 0
        records = [json.loads(p.read_text()) for p in sorted(results.iterdir())]
        assert all(r["resumed_from"] is None for r in records)
        assert all(r["step"] == 10 for r in records)
