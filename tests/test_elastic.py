"""Elastic recovery end-to-end: kill one rank of a 3-process job, relaunch
with the 2 survivors, resume from the committed State.

Reference behavior bar (VERDICT r1 #9): ``gloo_run.py:162-259`` kill-all
on any-rank failure + the §5.3/5.4 recovery conventions (rank-0 commit,
restore-then-broadcast, re-init with surviving hosts).  Membership change
on TPU means a fresh mesh: the relaunch IS the recovery mechanism, and
:class:`horovod_tpu.elastic.State` guarantees the survivors resume from
one consistent (step, params) point.
"""

import json
import os
import sys

import pytest

from horovod_tpu import native
from horovod_tpu.runner import launch
from horovod_tpu.runner.discovery import FixedHostDiscovery
from horovod_tpu.runner.elastic_driver import ElasticDriver, ElasticJobError
from horovod_tpu.runner.hosts import HostSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")
DRIVER_WORKER = os.path.join(REPO, "tests", "elastic_driver_worker.py")
HANG_WORKER = os.path.join(REPO, "tests", "elastic_hang_worker.py")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, phase, nproc, crash_rank=None):
    out = tmp_path / f"out.{phase}"
    results = tmp_path / f"results.{phase}"
    results.mkdir()
    env = {
        "PATH": os.environ.get("PATH", ""),
        "REPO": REPO,
        "PALLAS_AXON_POOL_IPS": "",  # keep subprocesses off the TPU
        "HOROVOD_NUM_PROC": str(nproc),
        "HOROVOD_JAX_PORT": str(_free_port()),
        "HOROVOD_NATIVE_PORT": str(_free_port()),
        "HOROVOD_CYCLE_TIME": "1",
        "ELASTIC_CKPT": str(tmp_path / "state.ckpt"),
        "ELASTIC_RESULTS": str(results),
    }
    if crash_rank is not None:
        env["ELASTIC_CRASH_RANK"] = str(crash_rank)
    rc = launch.launch_job(
        [sys.executable, WORKER],
        [HostSpec("localhost", 1)] * nproc,
        env=env,
        output_filename=str(out),
    )
    return rc, out, results


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
class TestElasticRecovery:
    @pytest.mark.slow
    def test_crash_relaunch_resume(self, tmp_path):
        # Phase 1: 3 ranks, rank 2 dies at step 7 (after the step-5
        # commit).  The launcher must kill the survivors — nonzero exit,
        # no final results, but a checkpoint at step 5.
        rc, out, results = _launch(tmp_path, 1, nproc=3, crash_rank=2)
        assert rc != 0, "crash must fail the whole job (kill-all)"
        assert not list(results.iterdir()), "no rank may have finished"
        assert (tmp_path / "state.ckpt").exists()
        crash_log = (out / "rank.2.stdout").read_text()
        assert "ELASTIC-WORKER-CRASH rank=2 step=7" in crash_log

        # Phase 2: relaunch with the 2 survivors; they restore step 5 and
        # run to completion with consistent state.
        rc, out, results = _launch(tmp_path, 2, nproc=2)
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / "rank.1.stderr").read_text()
        finals = sorted(results.iterdir())
        assert len(finals) == 2
        records = [json.loads(p.read_text()) for p in finals]
        assert all(r["resumed_from"] == 5 for r in records), records
        assert all(r["step"] == 10 for r in records), records
        assert all(r["size"] == 2 for r in records), records
        # consistent state across the survivors
        assert records[0]["checksum"] == pytest.approx(
            records[1]["checksum"]), records

    def test_fresh_run_completes_without_checkpoint(self, tmp_path):
        rc, out, results = _launch(tmp_path, 1, nproc=2)
        assert rc == 0
        records = [json.loads(p.read_text()) for p in sorted(results.iterdir())]
        assert all(r["resumed_from"] is None for r in records)
        assert all(r["step"] == 10 for r in records)


class TestElasticDriverUnit:
    """Driver policy with a mocked executor: restart/blacklist/abort
    decisions without spawning processes."""

    HOSTS = [HostSpec("localhost-a", 1), HostSpec("localhost-b", 1),
             HostSpec("localhost-c", 1)]

    def _driver(self, executor, hosts=None, **kw):
        kw.setdefault("min_np", 2)
        kw.setdefault("backoff_initial", 0.0)
        return ElasticDriver(
            ["x"], FixedHostDiscovery(hosts or self.HOSTS),
            _executor=executor, _sleep=lambda s: None, **kw)

    def test_crash_blacklists_and_restarts(self):
        envs = []

        def executor(cmd, env=None, **kw):
            envs.append(dict(env))
            if int(env["HOROVOD_ELASTIC_EPOCH"]) == 0 and \
                    env["HOROVOD_RANK"] == "1":
                return 17
            return 0

        d = self._driver(executor)
        assert d.run() == 0
        assert d.epoch_sizes == [3, 2]
        assert d.blacklist.hosts() == ["localhost-b"]
        # survivors re-rendezvous with a fresh epoch and fresh ports
        e1 = [e for e in envs if e["HOROVOD_ELASTIC_EPOCH"] == "1"]
        assert len(e1) == 2
        assert {e["HOROVOD_RANK"] for e in e1} == {"0", "1"}
        assert all(e["HOROVOD_NUM_PROC"] == "2" for e in e1)
        e0 = [e for e in envs if e["HOROVOD_ELASTIC_EPOCH"] == "0"]
        assert e0[0]["HOROVOD_JAX_PORT"] != e1[0]["HOROVOD_JAX_PORT"]

    def test_restart_exit_code_is_not_blamed(self):
        def executor(cmd, env=None, **kw):
            if int(env["HOROVOD_ELASTIC_EPOCH"]) == 0:
                return 75  # EXIT_CODE_RESTART: requested, not a failure
            return 0

        d = self._driver(executor)
        assert d.run() == 0
        assert d.blacklist.hosts() == []  # nobody blacklisted
        assert d.epoch_sizes == [3, 3]

    def test_below_min_np_aborts_clearly(self):
        d = self._driver(lambda cmd, env=None, **kw: 17,
                         hosts=self.HOSTS[:2])
        with pytest.raises(ElasticJobError, match="below min_np"):
            d.run()

    def test_reset_limit_aborts(self):
        d = self._driver(lambda cmd, env=None, **kw: 75,
                         hosts=self.HOSTS[:1], min_np=1, reset_limit=2)
        with pytest.raises(ElasticJobError, match="reset_limit"):
            d.run()
        assert d.resets == 3

    def test_max_np_caps_world(self):
        sizes = []

        def executor(cmd, env=None, **kw):
            sizes.append(env["HOROVOD_NUM_PROC"])
            return 0

        d = self._driver(executor, max_np=2)
        assert d.run() == 0
        assert sizes == ["2", "2"]

    def test_blacklist_cooldown_readmits_host(self):
        clock = [0.0]
        d = self._driver(lambda cmd, env=None, **kw: 0)
        d.blacklist._clock = lambda: clock[0]
        d.blacklist._cooldown = 10.0
        d.blacklist.add("localhost-b")
        assert d.blacklist.hosts() == ["localhost-b"]
        assert len(d.blacklist.filter(self.HOSTS)) == 2
        clock[0] = 11.0
        assert d.blacklist.hosts() == []
        assert len(d.blacklist.filter(self.HOSTS)) == 3


class TestElasticDriverHeartbeat:
    @pytest.mark.slow
    def test_stale_heartbeat_triggers_restart(self, tmp_path):
        """A hung (not dead) rank stops heartbeating: the driver must
        stale-detect it over the rendezvous KV, terminate the epoch, and
        restart on the surviving hosts."""
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": REPO,
            "ELASTIC_HANG_RANK": "1",
            "HOROVOD_ELASTIC_HEARTBEAT": "0.2",
        }
        d = ElasticDriver(
            [sys.executable, HANG_WORKER],
            FixedHostDiscovery([HostSpec("localhost-a", 1),
                                HostSpec("localhost-b", 1),
                                HostSpec("localhost-c", 1)]),
            min_np=2, env=env,
            heartbeat_interval=0.2, heartbeat_timeout=1.5,
            shutdown_grace=1.0, backoff_initial=0.1,
            output_filename=str(tmp_path / "out"))
        assert d.run() == 0
        assert d.epoch_sizes == [3, 2]
        assert d.blacklist.hosts() == ["localhost-b"]


@pytest.mark.skipif(not native.native_built(), reason="native lib unavailable")
class TestElasticDriverFaultInjection:
    """The acceptance drill: 3 ranks, min_np=2, one rank dies mid-training
    after a commit — the driver re-rendezvouses and training resumes on
    the survivors from the last committed step."""

    def _drive(self, tmp_path, *, nhosts, crash_rank=None, **driver_kw):
        results = tmp_path / "results"
        results.mkdir(exist_ok=True)
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": REPO,
            "PALLAS_AXON_POOL_IPS": "",  # keep subprocesses off the TPU
            "HOROVOD_CYCLE_TIME": "1",
            "ELASTIC_CKPT": str(tmp_path / "state.ckpt"),
            "ELASTIC_RESULTS": str(results),
        }
        if crash_rank is not None:
            env["ELASTIC_CRASH_RANK"] = str(crash_rank)
        hosts = [HostSpec(f"localhost-{c}", 1) for c in "abc"[:nhosts]]
        driver_kw.setdefault("min_np", 2)
        driver_kw.setdefault("backoff_initial", 0.1)
        driver_kw.setdefault("shutdown_grace", 20.0)
        d = ElasticDriver(
            [sys.executable, DRIVER_WORKER],
            FixedHostDiscovery(hosts), env=env,
            output_filename=str(tmp_path / "out"), **driver_kw)
        return d, results

    @pytest.mark.slow
    def test_crash_triggers_rerendezvous_and_resume(self, tmp_path):
        d, results = self._drive(tmp_path, nhosts=3, crash_rank=2)
        rc = d.run()
        assert rc == 0
        # one supervised restart: 3 ranks -> crash -> 2 survivors
        assert d.epoch_sizes == [3, 2]
        assert d.blacklist.hosts() == ["localhost-c"]

        finals = sorted(results.glob("final.e1.*.json"))
        assert len(finals) == 2, list(results.iterdir())
        records = [json.loads(p.read_text()) for p in finals]
        # resumed from the last committed step; no committed step lost
        assert all(r["resumed_from"] == 5 for r in records), records
        assert all(r["step"] == 10 for r in records), records
        assert all(r["size"] == 2 for r in records), records
        assert records[0]["checksum"] == pytest.approx(
            records[1]["checksum"]), records

        # step counter monotonic across the restart: epoch 1 replays
        # nothing before the committed step 5
        for r in (0, 1):
            steps = [int(s) for s in
                     (results / f"journal.e1.r{r}").read_text().split()]
            assert steps[0] == 6 and steps == sorted(steps), steps

    def test_below_min_np_aborts_not_hangs(self, tmp_path):
        d, _ = self._drive(tmp_path, nhosts=2, crash_rank=1)
        with pytest.raises(ElasticJobError, match="below min_np"):
            d.run()
