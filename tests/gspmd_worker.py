"""Multi-process COMPILED GSPMD worker — the pod deployment shape.

The reference's single product is N processes training synchronously (one
process per slot, ``run/gloo_run.py`` launch contract; every reference
test body runs under a 2-process launcher, SURVEY.md §4).  On a TPU pod
the equivalent shape is one process per HOST over a GLOBAL mesh: the
compiled GSPMD train step runs SPMD across all processes, input batches
are global ``jax.Array``s assembled from process-local shards, and
checkpoints are written collaboratively (each process writes the shards
it owns).

This worker runs that full lifecycle on N launcher-spawned processes of
``GSPMD_LOCAL_DEVICES`` virtual CPU devices each:

  1. ``hvd.init()`` → ``jax.distributed.initialize`` via the launcher env;
  2. global (dp×tp) mesh over all processes' devices;
  3. flagship Transformer + ``spmd.make_gspmd_train_step``;
  4. per-process input shards fed through ``DataLoader``'s global-array
     mode (``jax.make_array_from_process_local_data``);
  5. multihost orbax save at step 2, collaborative sharded restore,
     resume — replayed losses must be bit-identical;
  6. prints per-step loss/param-checksum BITS so the spawning test can
     compare the 2-process run against the single-process 8-device run.

With ``GSPMD_RESTORE_FROM`` set, the worker instead RESUMES from a
checkpoint another job topology wrote (cross-topology portability: a
pod checkpoint saved by N processes restores into M processes' mesh —
orbax re-places shards per this job's template shardings) and prints
the resumed losses for cross-job comparison.
"""

import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

from horovod_tpu._compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(int(os.environ.get("GSPMD_LOCAL_DEVICES", "4")))

import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import basics, checkpoint, spmd  # noqa: E402
from horovod_tpu.data import DataLoader  # noqa: E402
from horovod_tpu.models import transformer as T  # noqa: E402
from horovod_tpu.parallel.meshes import AXIS_ORDER, MeshSpec  # noqa: E402

CKPT_DIR = os.environ["GSPMD_CKPT_DIR"]
STEPS = 4
SAVE_AT = 2  # save after this many steps, then resume and replay
GLOBAL_BATCH = 16


def bits(x) -> str:
    return np.float32(float(x)).tobytes().hex()


def main() -> None:
    hvd.init()
    rank, nproc = basics.process_rank(), basics.num_processes()

    # Global 8-device mesh, (process, id)-lexicographic so the logical
    # mesh is identical whether 8 devices live in 1 process or 2.
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    assert len(devs) == 8, devs
    spec = MeshSpec(dp=4, tp=2)
    mesh = Mesh(np.array(devs).reshape(spec.shape), axis_names=AXIS_ORDER)

    cfg = T.TransformerConfig(
        vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq=16, dtype=np.float32, attention_impl="reference",
    )

    # Identical init on every process; device_put commits each leaf to its
    # GSPMD sharding (only the addressable shards transfer).
    p_specs = T.param_specs(cfg)
    params = jax.device_put(
        T.init_params(jax.random.PRNGKey(0), cfg),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), p_specs),
    )
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    step = spmd.make_gspmd_train_step(
        lambda p, b: T.loss_fn(p, b, cfg), opt,
        mesh=mesh, param_spec=p_specs, batch_spec=T.batch_specs(),
        donate=False,
    )

    # Deterministic dataset; the loader's global-array mode hands each
    # process only ITS rows and assembles one global array per batch.
    rng = np.random.RandomState(0)
    data = {
        "tokens": rng.randint(
            0, cfg.vocab_size, size=(64, cfg.max_seq)).astype(np.int32),
    }
    data["targets"] = np.roll(data["tokens"], -1, axis=1)
    tok_sharding = NamedSharding(mesh, T.batch_specs()["tokens"])
    loader = DataLoader(
        data, GLOBAL_BATCH, shuffle=True, seed=7, shard=False,
        prefetch=0, sharding=tok_sharding,
    )
    if nproc > 1:
        assert loader._global, "loader must be in global-array mode"
        assert len(loader._local_rows) == GLOBAL_BATCH // nproc, (
            loader._local_rows)
    batches = list(loader)
    assert len(batches) == STEPS
    assert batches[0]["tokens"].shape == (GLOBAL_BATCH, cfg.max_seq)

    restore_from = os.environ.get("GSPMD_RESTORE_FROM")
    if restore_from:
        # Cross-topology resume: the checkpoint was written by a job with
        # a DIFFERENT process layout over the same logical mesh; the
        # sharding-carrying template makes orbax place each shard on THIS
        # job's devices.  The logical program is identical, so the
        # resumed losses must be bit-identical to the writer's.
        # Scalar optimizer leaves (adam's count) from opt.init sit
        # UNCOMMITTED on one device; as restore targets they must carry
        # the mesh-wide placement or the restored (committed) array
        # conflicts with the 8-device params under jit.
        repl = NamedSharding(mesh, P())
        opt_t = jax.tree_util.tree_map(
            lambda l: (jax.device_put(l, repl)
                       if isinstance(l, jax.Array) and l.ndim == 0 else l),
            opt_state)
        template = {"params": params, "opt_state": opt_t, "step": 0}
        back = checkpoint.restore(os.path.join(restore_from, "state"),
                                  template)
        assert back["step"] == SAVE_AT
        rparams, ropt_state = back["params"], back["opt_state"]
        resume = []
        for i in range(SAVE_AT, STEPS):
            rparams, ropt_state, loss = step(rparams, ropt_state,
                                             batches[i])
            resume.append(bits(loss))
        print(f"GSPMD-RESUME-OK rank={rank} nproc={nproc} "
              f"resume={','.join(resume)}")
        hvd.shutdown()
        return

    repl = NamedSharding(mesh, P())

    def checksum(tree):
        # Host-side, order-deterministic: reshard each leaf to replicated
        # (pure data movement — an in-XLA sum's reduction tree is
        # topology-dependent and drifts by ulps between 1- and 2-process
        # runs), pull the full array, sum with numpy's fixed order.
        acc = np.float32(0)
        for leaf in jax.tree_util.tree_leaves(tree):
            full = np.asarray(jax.device_put(leaf, repl))
            acc = np.float32(acc + np.sum(full, dtype=np.float32))
        return acc

    losses = []
    saved = None
    for i in range(STEPS):
        params, opt_state, loss = step(params, opt_state, batches[i])
        losses.append(bits(loss))
        if i + 1 == SAVE_AT:
            # Multihost collaborative save: every process calls in; each
            # writes the shards it addresses.
            checkpoint.save(
                os.path.join(CKPT_DIR, "state"),
                {"params": params, "opt_state": opt_state, "step": i + 1},
            )
            saved = (params, opt_state)

    # --- resume: collaborative sharded restore, replay steps 2..4 -------
    template = {"params": saved[0], "opt_state": saved[1], "step": 0}
    back = checkpoint.restore(os.path.join(CKPT_DIR, "state"), template)
    assert back["step"] == SAVE_AT
    rparams, ropt_state = back["params"], back["opt_state"]
    for leaf in jax.tree_util.tree_leaves(rparams):
        assert isinstance(leaf, jax.Array)
        if nproc > 1:
            assert not leaf.is_fully_addressable  # restored SHARDED
    resume = []
    for i in range(SAVE_AT, STEPS):
        rparams, ropt_state, loss = step(rparams, ropt_state, batches[i])
        resume.append(bits(loss))
    assert resume == losses[SAVE_AT:], (
        f"resume diverged: {resume} vs {losses[SAVE_AT:]}")

    print(
        f"GSPMD-WORKER-OK rank={rank} nproc={nproc} "
        f"losses={','.join(losses)} resume={','.join(resume)} "
        f"check={bits(checksum(params))}"
    )
    hvd.shutdown()


main()
