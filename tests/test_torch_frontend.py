"""Torch frontend tests (role of the reference's test/test_torch.py: 46
tests of allreduce/async/inplace, DistributedOptimizer, state broadcast,
compression).  Single-process here; two-process protocol in
tests/torch_worker.py via the launcher."""

import os
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu.torch as hvd_torch  # noqa: E402
from horovod_tpu.runner import launch  # noqa: E402
from horovod_tpu.runner.hosts import HostSpec  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestTorchOps:
    def test_allreduce_identity(self, hvd):
        # Sum is chip-weighted: one process speaks for local_size() chips.
        x = torch.randn(4, 3)
        out = hvd_torch.allreduce(x, op=hvd_torch.Sum)
        assert torch.allclose(out, hvd_torch.local_size() * x, atol=1e-5)

    def test_allreduce_average_default(self, hvd):
        x = torch.randn(5)
        out = hvd_torch.allreduce(x)
        assert torch.allclose(out, x, atol=1e-6)

    def test_allreduce_inplace(self, hvd):
        x = torch.randn(4)
        orig = x.clone()
        out = hvd_torch.allreduce_(x, op=hvd_torch.Sum)
        assert out is x
        assert torch.allclose(x, hvd_torch.local_size() * orig, atol=1e-5)

    def test_async_poll_synchronize(self, hvd):
        import time

        x = torch.randn(8)
        h = hvd_torch.allreduce_async(x, op=hvd_torch.Sum)
        deadline = time.time() + 10
        while not hvd_torch.poll(h):
            assert time.time() < deadline
            time.sleep(0.001)
        out = hvd_torch.synchronize(h)
        assert torch.allclose(out, hvd_torch.local_size() * x, atol=1e-5)

    def test_allgather(self, hvd):
        x = torch.randn(3, 2)
        out = hvd_torch.allgather(x)
        assert torch.allclose(out, x)

    def test_broadcast(self, hvd):
        x = torch.randn(4)
        out = hvd_torch.broadcast(x, 0)
        assert torch.allclose(out, x)

    def test_compression_fp16(self, hvd):
        """Reference test_compression_fp16 (test_torch.py:1171): values
        survive the fp16 round trip within half precision."""
        x = torch.randn(64)
        out = hvd_torch.allreduce(x, op=hvd_torch.Sum,
                                  compression=hvd_torch.Compression.fp16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, hvd_torch.local_size() * x, atol=1e-1)

    def test_bfloat16_tensor(self, hvd):
        x = torch.randn(16).to(torch.bfloat16)
        out = hvd_torch.allreduce(x, op=hvd_torch.Sum)
        assert out.dtype == torch.bfloat16
        assert torch.allclose(out.float(),
                              hvd_torch.local_size() * x.float(), atol=1e-1)

    def test_int_tensor(self, hvd):
        x = torch.arange(6, dtype=torch.int32)
        out = hvd_torch.allreduce(x, op=hvd_torch.Sum)
        assert torch.equal(out, hvd_torch.local_size() * x)


class TestTorchAutograd:
    """The sync ops are autograd-differentiable (reference
    torch/mpi_ops.py:158-170 HorovodAllreduce/Allgather/Broadcast)."""

    def test_backward_through_allreduce(self, hvd):
        ls = hvd_torch.local_size()
        v = torch.tensor([1.0, 2.0], requires_grad=True)
        y = hvd_torch.allreduce(v * v, op=hvd_torch.Sum, name="tg.ar")
        y.sum().backward()
        # y = ls*v^2 (chip-weighted Sum); same-op backward is its VJP.
        assert torch.allclose(v.grad, ls * 2.0 * torch.tensor([1.0, 2.0]))

    def test_backward_through_allgather(self, hvd):
        v = torch.ones(2, 3, requires_grad=True)
        y = hvd_torch.allgather(v, name="tg.ag")
        (y * 3.0).sum().backward()
        # Process-level concat: FD-correct gradient, no chip factor.
        assert torch.allclose(v.grad, torch.full((2, 3), 3.0))

    def test_backward_through_broadcast(self, hvd):
        w = torch.tensor([5.0], requires_grad=True)
        z = hvd_torch.broadcast(w, 0, name="tg.bc")
        (z * 2.0).sum().backward()
        assert torch.allclose(w.grad, torch.tensor([2.0]))


class TestDistributedOptimizer:
    def _model(self):
        torch.manual_seed(0)
        return torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1))

    def test_wraps_and_trains(self, hvd):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        x = torch.randn(32, 4)
        y = x.sum(dim=1, keepdim=True)
        losses = []
        for _ in range(30):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::10]

    def test_keeps_optimizer_class(self, hvd):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=1e-3),
            named_parameters=model.named_parameters())
        assert isinstance(opt, torch.optim.Adam)
        assert opt.param_groups[0]["lr"] == 1e-3

    def test_duplicate_names_rejected(self, hvd):
        model = self._model()
        with pytest.raises(ValueError, match="duplicate"):
            hvd_torch.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1),
                named_parameters=[("p", p) for p in model.parameters()])

    @pytest.mark.parametrize("op_name", ["Average", "Adasum"])
    def test_default_names_unique_across_group(self, hvd, op_name):
        """No named_parameters: every param (not every param GROUP) must
        get its own auto-name, for both wrapper classes — a model with 4
        params in one group used to collide on 'noname.0'."""
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            op=getattr(hvd_torch, op_name))
        names = set(opt._parameter_names.values())
        assert len(names) == sum(1 for _ in model.parameters())
        x = torch.randn(8, 4)
        opt.zero_grad()
        torch.nn.functional.mse_loss(
            model(x), x.sum(dim=1, keepdim=True)).backward()
        opt.step()  # must not raise / deadlock

    def test_backward_passes_per_step(self, hvd):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        x = torch.randn(8, 4)
        y = x.sum(dim=1, keepdim=True)
        # two backwards accumulate locally, then one reduced step
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.zero_grad()

    def test_zero_grad_misuse_raises(self, hvd):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        loss = model(torch.randn(2, 4)).sum()
        loss.backward()
        with pytest.raises(AssertionError, match="zero_grad"):
            opt.zero_grad()
        opt.synchronize()  # drain

    def test_skip_synchronize(self, hvd):
        model = self._model()
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        loss = model(torch.randn(2, 4)).sum()
        loss.backward()
        opt.synchronize()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
        with opt.skip_synchronize():
            opt.step()


class TestStateBroadcast:
    def test_broadcast_parameters(self, hvd):
        model = torch.nn.Linear(3, 2)
        before = {k: v.clone() for k, v in model.state_dict().items()}
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.allclose(v, before[k])

    def test_broadcast_object(self, hvd):
        obj = {"lr": 0.1, "step": 7, "name": "adam"}
        out = hvd_torch.broadcast_object(obj, 0)
        assert out == obj

    def test_broadcast_optimizer_state(self, hvd):
        model = torch.nn.Linear(3, 2)
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        model(torch.randn(4, 3)).sum().backward()
        opt.step()
        hvd_torch.broadcast_optimizer_state(opt, root_rank=0)
        # state survives the round trip
        st = opt.state_dict()["state"]
        assert all("exp_avg" in s for s in st.values())


@pytest.mark.slow
class TestTorchMultiProcess:
    def _spawn(self, tmp_path, scenario, nproc):
        import socket

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        out = tmp_path / "out"
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": REPO,
            "PALLAS_AXON_POOL_IPS": "",
            "HOROVOD_NUM_PROC": str(nproc),
            "HOROVOD_JAX_PORT": str(free_port()),
            "HOROVOD_NATIVE_PORT": str(free_port()),
        }
        args = [sys.executable,
                os.path.join(REPO, "tests", "torch_worker.py")]
        if scenario:
            args.append(scenario)
        rc = launch.launch_job(
            args,
            [HostSpec("localhost", 1)] * nproc,
            env=env,
            output_filename=str(out),
        )
        assert rc == 0, (out / "rank.0.stderr").read_text() + (
            out / f"rank.{nproc - 1}.stderr").read_text()
        for r in range(nproc):
            assert "TORCH-WORKER-OK" in (out / f"rank.{r}.stdout").read_text()

    def test_two_process_torch(self, tmp_path):
        self._spawn(tmp_path, None, 2)

    def test_adasum_delta_two_process(self, tmp_path):
        """Delta-model Adasum vs the pairwise oracle, 2 ranks (reference
        test_adasum_* parity)."""
        self._spawn(tmp_path, "adasum", 2)

    def test_adasum_delta_four_process(self, tmp_path):
        """Same at 4 ranks: two VHDD rounds exercise the recursion."""
        self._spawn(tmp_path, "adasum", 4)

    def test_adasum_delta_three_process(self, tmp_path):
        """Non-power-of-2 rank count: the eager Adasum falls back to
        gather + the serial pairwise oracle (the reference ERRORS here —
        adasum_mpi.cc:52-67; we degrade gracefully instead), and the
        delta optimizer must still match adasum_reduce_stack exactly."""
        self._spawn(tmp_path, "adasum", 3)
