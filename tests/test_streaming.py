"""SSE token streaming + cancel-on-disconnect (serving/sse.py, the
``stream=true`` path of serving/server.py, and the router's mid-stream
failover in serving/router/server.py).

The gold checks:

* a streamed response's concatenated token events == the non-streamed
  200 body == the per-request ``sample_decode`` oracle, indices
  gapless;
* a client that disconnects mid-stream CANCELS the request — the slot
  (and its pages) is reclaimed within a tick, counted in
  ``serving_disconnects_total``;
* the router proxies the chunked body through live, and a replica that
  dies MID-STREAM is failed over from its journal/descriptor with no
  duplicated and no dropped token events on the client's wire — the
  stream stays byte-identical to an uninterrupted run (the SIGKILL
  subprocess drill proves it against a real kill).
"""

import dataclasses
import http.client
import json
import os
import signal
import socket
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.serving import sse
from horovod_tpu.serving.router import (
    ReplicaEndpoint,
    ReplicaRegistry,
    ReplicaSpec,
    ReplicaSupervisor,
    RouterServer,
)

pytestmark = pytest.mark.streaming


def _cfg(**kw):
    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _oracle(params, cfg, prompt, steps, *, temperature=0.0, top_k=0,
            top_p=0.0, seed=0):
    return np.asarray(T.sample_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg,
        rng=jax.random.PRNGKey(seed), temperature=temperature,
        top_k=top_k, top_p=top_p))[0].tolist()


def _post(host, port, body, timeout=60, headers=None):
    c = http.client.HTTPConnection(host, port, timeout=timeout)
    c.request("POST", "/generate", body=json.dumps(body).encode(),
              headers=headers or {})
    return c, c.getresponse()


def _tokens(events):
    return [p["token"] for k, p in events if k == "token"]


def _indices(events):
    return [p["i"] for k, p in events if k == "token"]


def _terminal(events, kind):
    out = [p for k, p in events if k == kind]
    assert len(out) == 1, f"expected one {kind} event: {events}"
    return out[0]


# ---------------------------------------------------------------------------
# wire-format plumbing
# ---------------------------------------------------------------------------


class TestSSEPlumbing:
    def test_event_round_trip_any_chunking(self):
        frames = (sse.event_bytes("token", {"i": 0, "token": 5})
                  + sse.event_bytes("done", {"tokens": [5],
                                             "finish_reason": "eos"}))
        for step in (1, 3, 7, len(frames)):
            p = sse.SSEParser()
            evs = []
            for off in range(0, len(frames), step):
                evs.extend(p.feed(frames[off:off + step]))
            assert [k for k, _ in evs] == ["token", "done"]
            assert evs[0][1] == {"i": 0, "token": 5}
            assert evs[1][1]["finish_reason"] == "eos"

    def test_unparseable_data_survives(self):
        p = sse.SSEParser()
        evs = p.feed(b"event: token\ndata: not-json\n\n")
        assert evs == [("token", {"_raw": "not-json"})]


# ---------------------------------------------------------------------------
# the serving server's stream
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stream_server(model):
    """One warmed engine + HTTP server for the whole class; a slow
    detokenizer (~5 ms/token) keeps generation observable so the
    disconnect test can land mid-stream deterministically."""
    params, cfg = model
    cfg = _cfg(max_seq=128)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    def slow_detok(t):
        time.sleep(0.005)
        return f"<{t}>"

    eng = serving.InferenceEngine(
        params, cfg,
        serving.EngineConfig(n_slots=2, max_len=128, tick_timeout=0),
        detokenize=slow_detok)
    eng.warmup([1, 4])
    srv = serving.ServingServer(eng, port=0).start()
    yield params, cfg, eng, srv
    srv.stop(drain_timeout=5)


@pytest.mark.serving
class TestServerStreaming:
    def test_stream_equals_nonstream_equals_oracle(self, stream_server):
        params, cfg, eng, srv = stream_server
        host, port = srv.address
        body = {"tokens": [3, 4, 5], "max_new_tokens": 8,
                "temperature": 1.2, "seed": 7}
        c, r = _post(host, port, body)
        plain = json.loads(r.read())
        c.close()
        c, r = _post(host, port, {**body, "stream": True})
        assert r.status == 200
        assert "text/event-stream" in r.getheader("Content-Type")
        assert r.getheader("X-Trace-Id")
        events = sse.read_stream(r)
        c.close()
        done = _terminal(events, "done")
        want = _oracle(params, cfg, [3, 4, 5], 8, temperature=1.2,
                       seed=7)
        assert _tokens(events) == done["tokens"] == plain["tokens"] \
            == want
        assert _indices(events) == list(range(8))
        # streamed detokenization rides the events
        assert all("text" in p for k, p in events if k == "token")
        assert done["finish_reason"] == "length"
        assert done["ttft_ms"] is not None
        snap = eng.stats()
        assert snap["streamed_tokens"] >= 8
        assert snap["streamed_ttfb_seconds"]["count"] >= 1

    def test_greedy_stream_default(self, stream_server):
        params, cfg, eng, srv = stream_server
        host, port = srv.address
        c, r = _post(host, port, {"tokens": [9, 2], "max_new_tokens": 5,
                                  "stream": True})
        events = sse.read_stream(r)
        c.close()
        assert _tokens(events) == _oracle(params, cfg, [9, 2], 5)

    def test_eos_finish_streams_done(self, stream_server):
        params, cfg, eng, srv = stream_server
        want = _oracle(params, cfg, [3, 4, 5], 8)
        eos = want[2]  # force an early EOS retirement
        c, r = _post(host := srv.address[0], port := srv.address[1],
                     {"tokens": [3, 4, 5], "max_new_tokens": 8,
                      "eos_id": eos, "stream": True})
        events = sse.read_stream(r)
        c.close()
        done = _terminal(events, "done")
        assert done["finish_reason"] == "eos"
        # retires at the FIRST occurrence of the eos value
        assert _tokens(events) == want[:want.index(eos) + 1]

    def test_submit_rejection_is_plain_json(self, stream_server):
        params, cfg, eng, srv = stream_server
        host, port = srv.address
        # too long -> 413, never a stream
        c, r = _post(host, port, {"tokens": [1], "max_new_tokens": 4096,
                                  "stream": True})
        assert r.status == 413
        assert "json" in r.getheader("Content-Type")
        json.loads(r.read())
        c.close()
        # bad sampling param -> 400
        c, r = _post(host, port, {"tokens": [1], "temperature": -1,
                                  "stream": True})
        assert r.status == 400
        c.close()

    def test_disconnect_cancels_and_reclaims_slot(self, stream_server):
        params, cfg, eng, srv = stream_server
        host, port = srv.address
        before = eng.metrics.disconnects.value
        c, r = _post(host, port, {"tokens": [9], "max_new_tokens": 120,
                                  "temperature": 1.0, "seed": 3,
                                  "stream": True})
        assert r.status == 200
        parser = sse.SSEParser()
        got = []
        while len(got) < 3:
            got.extend(parser.feed(r.read1(128)))
        # hard hangup (RST) mid-stream
        c.sock.shutdown(socket.SHUT_RDWR)
        c.close()
        deadline = time.monotonic() + 20.0
        while eng.slots.active_count and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.slots.active_count == 0, "slot leaked past disconnect"
        assert eng.metrics.disconnects.value == before + 1
        # the engine never decoded to the full budget for a dead client
        assert eng.metrics.streamed_tokens.value < \
            eng.metrics.tokens_generated.value + 120


# ---------------------------------------------------------------------------
# the router's streamed proxy + mid-stream failover
# ---------------------------------------------------------------------------


def _stack(model, n=2, max_len=128, detok_sleep=0.0, max_restarts=3):
    """N in-process replicas (full engines + HTTP servers, journal
    files armed) behind a polled registry + router."""
    params, cfg = model
    cfg = _cfg(max_seq=max_len)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    tmp = tempfile.mkdtemp(prefix="stream_stack_")

    def detok(t):
        if detok_sleep:
            time.sleep(detok_sleep)
        return f"<{t}>"

    servers = []
    for i in range(n):
        eng = serving.InferenceEngine(
            params, cfg,
            serving.EngineConfig(
                n_slots=2, max_len=max_len, tick_timeout=0,
                max_restarts=max_restarts,
                journal_path=os.path.join(tmp, f"r{i}.journal.jsonl")),
            detokenize=detok if detok_sleep else None)
        eng.warmup([1, 4])
        servers.append(serving.ServingServer(eng, port=0).start())
    reg = ReplicaRegistry(poll_interval=0.1)
    for i, s in enumerate(servers):
        h, p = s.address
        reg.add(ReplicaEndpoint(f"r{i}", h, p,
                                journal_path=s.engine.journal.path))
    rt = RouterServer(reg, port=0, max_attempts=4,
                      retry_backoff=0.05).start()
    deadline = time.monotonic() + 10.0
    while (len(reg.in_rotation()) < n
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert len(reg.in_rotation()) == n
    return params, cfg, servers, reg, rt


def _teardown(servers, rt):
    rt.stop()
    for s in servers:
        try:
            s.stop(drain_timeout=2)
        except Exception:
            pass


@pytest.mark.router
class TestRouterStreaming:
    @pytest.mark.slow
    def test_streamed_proxy_pass_through(self, model):
        # Slow (PR 17 budget pass): router stack spin-up is ~8 s; the
        # mid-stream failover test below proxies a live stream through
        # the same path (a strict superset) and stays tier-1.
        params, cfg, servers, reg, rt = _stack(model, n=1, max_len=48)
        try:
            host, port = rt.address
            body = {"tokens": [3, 4, 5], "max_new_tokens": 8,
                    "temperature": 1.2, "seed": 7, "stream": True}
            c, r = _post(host, port, body,
                         headers={"X-Trace-Id": "t" * 16})
            assert r.status == 200
            assert r.getheader("X-Trace-Id") == "t" * 16
            assert r.getheader("X-Router-Replica") == "r0"
            events = sse.read_stream(r)
            c.close()
            want = _oracle(params, cfg, [3, 4, 5], 8, temperature=1.2,
                           seed=7)
            assert _tokens(events) == want
            assert _terminal(events, "done")["tokens"] == want
            assert _indices(events) == list(range(8))
        finally:
            _teardown(servers, rt)

    def test_midstream_failover_resumes_without_dupes(self, model):
        """Terminate the serving replica's engine mid-stream: the
        in-band error event's resume descriptor fails the stream over,
        the survivor continues from the frontier, and the client's
        wire shows every token exactly once — byte-identical to the
        uninterrupted sampled oracle."""
        params, cfg, servers, reg, rt = _stack(model, detok_sleep=0.02,
                                               max_restarts=0)
        try:
            host, port = rt.address
            N = 60
            c, r = _post(host, port,
                         {"tokens": [9, 11], "max_new_tokens": N,
                          "temperature": 1.1, "seed": 5,
                          "timeout_ms": 60000, "stream": True},
                         timeout=120)
            assert r.status == 200
            parser = sse.SSEParser()
            events = []
            while len(_tokens(events)) < 5:
                events.extend(parser.feed(r.read1(256)))
            victim = int(r.getheader("X-Router-Replica")[1])
            servers[victim].engine.terminate("chaos: killed mid-stream")
            while True:
                data = r.read1(512)
                if not data:
                    break
                events.extend(parser.feed(data))
            c.close()
            done = _terminal(events, "done")
            want = _oracle(params, cfg, [9, 11], N, temperature=1.1,
                           seed=5)
            assert _indices(events) == list(range(N)), \
                "duplicated or dropped token events"
            assert _tokens(events) == want
            assert done["tokens"] == want
            assert done["resumed"] is True
            assert done["resume_carried_tokens"] >= 1
            assert reg.metrics.resume_failovers.value >= 1
            # the survivor, not the corpse, finished the request
            other = servers[1 - victim].engine
            assert other.metrics.completed.value >= 1
        finally:
            _teardown(servers, rt)

    def test_nonresumable_stream_ends_typed_not_crashed(self, model):
        """A streamed body WITHOUT max_new_tokens is not resumable (the
        router cannot rewrite it): when its replica dies after token
        events already reached the client, the stream must end with a
        terminal ``stream_interrupted`` error event — never a re-issued
        from-scratch duplicate stream, and never a dead handler with no
        terminal event (regression: the failover path used to KeyError
        on the body rewrite)."""
        params, cfg, servers, reg, rt = _stack(model, detok_sleep=0.02,
                                               max_restarts=0)
        try:
            host, port = rt.address
            c, r = _post(host, port,
                         {"tokens": [9, 11], "temperature": 1.1,
                          "seed": 5, "stream": True},  # no max_new
                         timeout=60)
            assert r.status == 200
            parser = sse.SSEParser()
            events = []
            while len(_tokens(events)) < 3:
                events.extend(parser.feed(r.read1(256)))
            victim = int(r.getheader("X-Router-Replica")[1])
            servers[victim].engine.terminate("chaos")
            while True:
                data = r.read1(512)
                if not data:
                    break
                events.extend(parser.feed(data))
            c.close()
            err = _terminal(events, "error")
            # In-band death relays the replica's typed engine_failed;
            # connection-level death (e.g. SIGKILL) gets the router's
            # stream_interrupted.  Either way: ONE terminal typed
            # error, no crash, no duplicate re-issued stream.
            assert err["type"] in ("engine_failed",
                                   "stream_interrupted")
            assert _indices(events) == list(range(len(_tokens(events))))
        finally:
            _teardown(servers, rt)


class _CutStreamReplica:
    """A fake replica that answers /generate with an SSE stream of
    ``n_tokens`` token events and then KILLS the connection without a
    terminal event — the wire signature of a SIGKILL mid-stream —
    while /stats keeps it in rotation."""

    def __init__(self, n_tokens=3):
        import http.server

        fake = self

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = json.dumps({
                    "queue_depth": 0, "occupancy": 0.0,
                    "engine_state": "healthy",
                    "heartbeat_age_s": 0.01}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i in range(fake.n_tokens):
                    data = sse.event_bytes("token",
                                           {"i": i, "token": 40 + i})
                    self.wfile.write(b"%x\r\n" % len(data) + data
                                     + b"\r\n")
                # die mid-stream: no terminal event, dead socket
                self.connection.shutdown(socket.SHUT_RDWR)
                self.connection.close()

        from http.server import ThreadingHTTPServer

        self.n_tokens = n_tokens
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def address(self):
        return self.httpd.server_address[:2]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.mark.router
class TestRouterStreamConnectionDeath:
    def test_connection_cut_nonresumable_terminal_error(self):
        """Connection death mid-stream with a NON-resumable body (no
        max_new_tokens): the router must end the client's stream with
        a terminal ``stream_interrupted`` error — the regression was a
        KeyError rewriting the body for a retry, which killed the
        handler with no terminal event at all."""
        fake = _CutStreamReplica(n_tokens=3)
        reg = ReplicaRegistry(poll_interval=0.1)
        h, p = fake.address
        reg.add(ReplicaEndpoint("rX", h, p))
        rt = RouterServer(reg, port=0, max_attempts=3,
                          retry_backoff=0.01).start()
        try:
            deadline = time.monotonic() + 5.0
            while not reg.in_rotation() and time.monotonic() < deadline:
                time.sleep(0.02)
            host, port = rt.address
            c, r = _post(host, port, {"tokens": [1, 2],
                                      "temperature": 1.0,
                                      "stream": True}, timeout=30)
            assert r.status == 200
            events = sse.read_stream(r)
            c.close()
            assert _tokens(events) == [40, 41, 42]
            err = _terminal(events, "error")
            assert err["type"] == "stream_interrupted"
        finally:
            rt.stop()
            fake.close()


# ---------------------------------------------------------------------------
# chaos: a real SIGKILL under a live stream (subprocess replicas)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
class TestStreamingFrontTierChaos:
    def test_sigkill_mid_stream_no_dupes_no_drops(self, model):
        """ACCEPTANCE (ISSUE 13): SIGKILL a replica while it is
        actively streaming a SAMPLED request.  The router reads the
        dead replica's journal post-mortem, re-emits only what the
        client never received, and continues the stream on the
        survivor — the client's SSE stream ends with gapless indices
        and a token sequence byte-identical to ``sample_decode`` at
        the request's seed.  No duplicated events, no dropped tokens,
        ``resumed: true`` on the terminal done event."""
        params, cfg = model
        spec = ReplicaSpec(seed=0, slots=4, warm=(8,),
                           tick_timeout=30.0, drain_timeout=3.0,
                           request_timeout=90.0)
        reg = ReplicaRegistry(poll_interval=0.15, poll_timeout=1.0,
                              heartbeat_stale=5.0)
        journal_dir = tempfile.mkdtemp(prefix="stream_chaos_")
        sup = ReplicaSupervisor(spec, 2, registry=reg,
                                unhealthy_grace=1.5,
                                shutdown_grace=2.0,
                                backoff_initial=0.1,
                                journal_dir=journal_dir)
        rt = RouterServer(reg, port=0, max_attempts=4,
                          retry_backoff=0.05, proxy_timeout=120.0,
                          resume_lookup=sup.resume_lookup)
        sup.start()
        rt.start()
        try:
            assert sup.wait_ready(timeout=240), "replicas never ready"
            host, port = rt.address
            steps = 40
            trace = "f" * 16
            kill_done = threading.Event()

            def kill_streaming_replica():
                """SIGKILL whichever replica's journal shows OUR
                request mid-decode — enough emitted to force a real
                carry, enough remaining that the kill lands before
                retirement."""
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    for h in sup.replicas():
                        try:
                            live = serving.RequestJournal.read_live(
                                sup._journal_paths[h.rid])
                        except Exception:
                            continue
                        d = live.get(trace)
                        if (d is not None and
                                5 <= len(d["emitted_tokens"])
                                <= steps - 15):
                            os.kill(h.pid, signal.SIGKILL)
                            kill_done.set()
                            return
                    time.sleep(0.01)

            killer = threading.Thread(target=kill_streaming_replica,
                                      daemon=True)
            c, r = _post(host, port,
                         {"tokens": [9, 11], "max_new_tokens": steps,
                          "temperature": 1.1, "seed": 5,
                          "timeout_ms": 90000, "stream": True},
                         timeout=120, headers={"X-Trace-Id": trace})
            assert r.status == 200
            killer.start()
            events = sse.read_stream(r)
            c.close()
            killer.join(5.0)
            assert kill_done.is_set(), \
                "the kill never landed mid-stream (request too fast?)"
            done = _terminal(events, "done")
            want = _oracle(params, cfg, [9, 11], steps,
                           temperature=1.1, seed=5)
            assert _indices(events) == list(range(steps)), \
                "duplicated or dropped token events across the kill"
            assert _tokens(events) == want
            assert done["tokens"] == want
            assert done.get("resumed") is True
            assert done.get("resume_carried_tokens", 0) >= 5
            assert reg.metrics.resume_failovers.value >= 1
        finally:
            rt.stop()
            sup.stop(drain=False)
