"""Hierarchical (two-level) collectives over the (cross, local) mesh.

Reference: ``NCCLHierarchicalAllreduce`` (``ops/nccl_operations.cc:162-354``)
— reduce-scatter within the node, cross-node allreduce, allgather within the
node — and ``MPIHierarchicalAllgather`` (``ops/mpi_operations.cc``), enabled
by ``HOROVOD_HIERARCHICAL_ALLREDUCE`` / ``HOROVOD_HIERARCHICAL_ALLGATHER``
(``common/common.h:76-77``).  Here the 8 virtual devices stand in for a
4-host × 2-chip topology.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import basics, spmd
from horovod_tpu.ops import collectives as C

CROSS, LOCAL = 4, 2
N = CROSS * LOCAL


def hier_mesh():
    devs = np.array(jax.devices()[:N], dtype=object).reshape(CROSS, LOCAL)
    return jax.sharding.Mesh(devs, (basics.CROSS_AXIS, basics.LOCAL_AXIS))


def _per_worker(shape, seed=0):
    return np.random.RandomState(seed).randn(N, *shape).astype(np.float32)


def _jit_over_hier(fn, out_spec=P((basics.CROSS_AXIS, basics.LOCAL_AXIS))):
    axes = P((basics.CROSS_AXIS, basics.LOCAL_AXIS))
    return jax.jit(
        spmd.shard(
            lambda x: fn(x[0])[None],
            in_specs=(axes,),
            out_specs=out_spec,
            mesh=hier_mesh(),
        )
    )


class TestHierarchicalAllreduce:
    @pytest.mark.parametrize("shape", [(4, 6), (3,), (5, 3)])
    def test_numerics_match_flat(self, monkeypatch, shape):
        """Hierarchical result == flat psum result == numpy sum (covers the
        padding path: 3 and 15 elements are not divisible by local=2)."""
        x = _per_worker(shape)
        monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE", raising=False)
        flat = np.asarray(_jit_over_hier(lambda t: hvd.allreduce(t, hvd.Sum))(x))
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        hier = np.asarray(_jit_over_hier(lambda t: hvd.allreduce(t, hvd.Sum))(x))
        expect = x.sum(axis=0)
        for i in range(N):
            np.testing.assert_allclose(flat[i], expect, rtol=1e-4)
            np.testing.assert_allclose(hier[i], expect, rtol=1e-4)

    def test_average(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        x = _per_worker((4, 4))
        out = np.asarray(_jit_over_hier(lambda t: hvd.allreduce(t, hvd.Average))(x))
        np.testing.assert_allclose(out[0], x.mean(axis=0), rtol=1e-4)

    def test_flag_changes_emitted_collectives(self, monkeypatch):
        """The launcher flag must actually change the program: hierarchical
        lowers to reduce-scatter + all-reduce + all-gather, flat to one
        all-reduce (VERDICT round-1 item #2)."""
        x = _per_worker((8, 8))

        def lower(flag):
            if flag:
                monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
            else:
                monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE", raising=False)
            return _jit_over_hier(lambda t: hvd.allreduce(t, hvd.Sum)).lower(x).as_text()

        hier_hlo = lower(True)
        flat_hlo = lower(False)
        assert "reduce_scatter" in hier_hlo
        assert "reduce_scatter" not in flat_hlo

    def test_axis_resolution_under_hier_mesh(self, monkeypatch):
        """allreduce with axis_name=None inside a (cross, local) shard_map
        resolves to both axes (not the unbound flat axis)."""
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        x = _per_worker((2, 2))
        out = np.asarray(
            _jit_over_hier(lambda t: hvd.allreduce(t, hvd.Sum, axis_name=None))(x)
        )
        np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-4)


class TestHierarchicalAllgather:
    def test_numerics_and_order_match_flat(self, monkeypatch):
        x = _per_worker((3, 5))
        monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLGATHER", raising=False)
        flat = np.asarray(_jit_over_hier(hvd.allgather)(x))
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "1")
        hier = np.asarray(_jit_over_hier(hvd.allgather)(x))
        expect = x.reshape(-1, 5)
        for i in range(N):
            np.testing.assert_allclose(flat[i], expect, rtol=1e-6)
            np.testing.assert_allclose(hier[i], expect, rtol=1e-6)

    def test_flag_changes_emitted_collectives(self, monkeypatch):
        x = _per_worker((4, 4))

        def lower(flag):
            if flag:
                monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "1")
            else:
                monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLGATHER", raising=False)
            return _jit_over_hier(hvd.allgather).lower(x).as_text()

        hier_hlo = lower(True)
        flat_hlo = lower(False)
        # Staged path: two all_gathers (one per axis); flat path: one joint.
        assert hier_hlo.count("all_gather") > flat_hlo.count("all_gather")


class TestTrainStepWiring:
    def test_make_train_step_uses_hier_mesh(self, monkeypatch):
        """End-to-end: env flag → make_train_step builds over the
        hierarchical mesh and the gradient reduction goes through the
        two-level path (reduce_scatter visible in the lowered program)."""
        import optax

        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        # The session context's hierarchical mesh is 1 host x 8 local
        # (single process); substitute the 4x2 mesh explicitly to model
        # multi-host.
        mesh = hier_mesh()
        axis = (basics.CROSS_AXIS, basics.LOCAL_AXIS)

        def loss_fn(params, batch):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.ones((6, 3))}
        step = spmd.make_train_step(
            loss_fn, opt, mesh=mesh, axis=axis, donate=False
        )
        opt_state = opt.init(params)
        batch = {
            "x": jnp.ones((16, 6)),
            "y": jnp.zeros((16, 3)),
        }
        hlo = step.lower(params, opt_state, batch).as_text()
        assert "reduce_scatter" in hlo
        params2, opt_state2, loss = step(params, opt_state, batch)
        assert np.isfinite(float(loss))

    def test_env_default_selects_hier_mesh(self, monkeypatch):
        """hierarchical=None + env flag set → the step binds the context's
        (cross, local) axes instead of the flat axis."""
        import optax

        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))

        def loss_fn(params, batch):
            return jnp.mean((batch @ params) ** 2)

        step = spmd.make_train_step(loss_fn, opt, donate=False)
        params = jnp.ones((4, 2))
        opt_state = opt.init(params)
        batch = jnp.ones((16, 4))
        params2, opt_state2, loss = step(params, opt_state, batch)
        assert np.isfinite(float(loss))
