"""Traffic-pattern contracts of the eager data-plane programs.

VERDICT r1 weak #3: the round-1 eager broadcast/allgather/alltoall/adasum
all materialized a full P-way concatenation on every process (O(P x tensor)
traffic per op).  These tests compile the round-2 programs over the 8-device
mesh and assert on the emitted collectives — the machine-checkable proxy for
"bytes proportional to tensor, not P x tensor":

* rooted broadcast: no ``all-gather`` in the module (owner's block moves by
  masked all-reduce / collective-permute);
* reducescatter: a true ``reduce-scatter`` op;
* alltoall: a true ``all-to-all`` op;
* eager Adasum: ``collective-permute`` partner exchanges only (the log2(P)
  VHDD rounds of ``adasum.h:194-338``), no gather.

Numerics of each program are checked against serial oracles on the same
mesh.  Multi-process execution of the same code paths is covered by
tests/native_worker.py (2 real processes).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops import adasum as adasum_mod
from horovod_tpu.ops import collectives as C

COLL = re.compile(
    r"(all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute)"
)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()), ("proc",))


def _sharded(mesh, x):
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("proc")))


def _collectives_of(prog, arg):
    return set(COLL.findall(prog.lower(arg).compile().as_text()))


class TestRootedBroadcast:
    def test_no_allgather_in_hlo(self, mesh):
        a = _sharded(mesh, np.zeros((8, 128), np.float32))
        colls = _collectives_of(C._pick_program(mesh, "proc", 3), a)
        assert "all-gather" not in colls, colls
        assert colls, "expected a collective to move the root's block"

    def test_numerics(self, mesh):
        x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
        out = np.asarray(C._pick_program(mesh, "proc", 5)(_sharded(mesh, x)))
        np.testing.assert_allclose(out, x[5])


class TestEagerReducescatter:
    def test_true_reduce_scatter_in_hlo(self, mesh):
        a = _sharded(mesh, np.zeros((8, 64), np.float32))
        colls = _collectives_of(
            C._reducescatter_program(mesh, "proc", C.Sum), a
        )
        assert colls == {"reduce-scatter"}, colls

    @pytest.mark.parametrize("op", [C.Sum, C.Average])
    def test_numerics(self, mesh, op):
        x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
        out = np.asarray(
            jax.device_get(
                C._reducescatter_program(mesh, "proc", op)(_sharded(mesh, x))
            )
        )
        expect = x.sum(0).reshape(8, 2)
        if op == C.Average:
            expect = expect / 8
        np.testing.assert_allclose(out, expect)


class TestEagerAlltoall:
    def test_true_all_to_all_in_hlo(self, mesh):
        a = _sharded(mesh, np.zeros((8, 8, 4), np.float32))
        colls = _collectives_of(C._alltoall_program(mesh, "proc"), a)
        assert colls == {"all-to-all"}, colls

    def test_numerics(self, mesh):
        x = np.arange(8 * 8 * 2, dtype=np.float32).reshape(8, 8, 2)
        out = np.asarray(
            jax.device_get(C._alltoall_program(mesh, "proc")(_sharded(mesh, x)))
        )
        expect = np.stack(
            [np.stack([x[p, q] for p in range(8)]) for q in range(8)]
        )
        np.testing.assert_allclose(out, expect)


class TestEagerAdasumVHDD:
    def test_permute_only_in_hlo(self, mesh):
        a = _sharded(mesh, np.ones((8, 32), np.float32))
        colls = _collectives_of(adasum_mod.vhdd_program(mesh, "proc"), a)
        assert "all-gather" not in colls, colls
        assert "collective-permute" in colls, colls

    def test_log2_rounds(self, mesh):
        a = _sharded(mesh, np.ones((8, 32), np.float32))
        txt = (
            adasum_mod.vhdd_program(mesh, "proc").lower(a).compile().as_text()
        )
        # Count permute INSTRUCTION DEFINITIONS (opcode after "="), not
        # raw substring hits: an instruction's %collective-permute.N
        # name reappears at every operand reference (the VHDD a/b
        # orientation selects reference each result twice), so a plain
        # findall counts each round ~4x.  3 VHDD rounds for P=8; async
        # lowering may split each into a start+done pair.
        # "[^\n]*?" (not "\S+") between "=" and the opcode: an async
        # start's result is a TUPLE type printed with spaces.  Operand
        # references never match — they are not followed by "(".
        n_permutes = len(re.findall(
            r"=[^\n]*?\bcollective-permute(?:-start)?\(", txt))
        assert n_permutes <= 3, txt

    def test_matches_serial_oracle(self, mesh):
        rng = np.random.RandomState(0)
        x = rng.randn(8, 16).astype(np.float32)
        out = np.asarray(
            jax.device_get(adasum_mod.vhdd_program(mesh, "proc")(_sharded(mesh, x)))
        )
        oracle = np.asarray(adasum_mod.adasum_reduce_stack(x))
        for r in range(8):
            np.testing.assert_allclose(out[r], oracle, rtol=1e-5)


class TestSingleProcessFallbacks:
    """cross_size()==1 in the test session: the public eager entry points
    exercise the local-identity paths and input validation."""

    def test_reducescatter_eager_single(self, hvd):
        # Chip-weighted Sum: the single process speaks for all its chips.
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(
            hvd.reducescatter(x, hvd.Sum), hvd.local_size() * x)
        np.testing.assert_allclose(hvd.reducescatter(x, hvd.Average), x)

    def test_reducescatter_async_roundtrip(self, hvd):
        x = np.arange(8, dtype=np.float32)
        h = hvd.reducescatter_async(x, hvd.Sum)
        np.testing.assert_allclose(
            hvd.synchronize(h), hvd.local_size() * x)

    def test_reducescatter_rejects_bad_op(self, hvd):
        with pytest.raises(ValueError):
            hvd.reducescatter(np.zeros(4, np.float32), "Bogus")

    def test_alltoall_uneven_splits_validated(self, hvd):
        with pytest.raises(ValueError):
            C._eager_alltoall(np.zeros(4, np.float32), splits=[3, 3])
