"""Topology/init tests (reference: test_horovod_rank / test_horovod_size
in test/test_tensorflow.py:68-99 region and test_torch.py)."""

import jax
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import basics


def test_init_idempotent():
    assert hvd.is_initialized()
    hvd.init()  # second call is a no-op
    assert hvd.is_initialized()


def test_size_is_device_count():
    assert hvd.size() == jax.device_count() == 8


def test_local_and_cross():
    assert hvd.local_size() == jax.local_device_count()
    assert hvd.cross_size() == jax.process_count() == 1
    assert hvd.cross_rank() == 0
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0


def test_homogeneous_and_hier_mesh():
    assert hvd.is_homogeneous()
    hm = hvd.hierarchical_mesh()
    assert hm is not None
    assert hm.axis_names == (basics.CROSS_AXIS, basics.LOCAL_AXIS)
    assert hm.devices.size == 8


def test_mesh_axis():
    m = hvd.mesh()
    assert m.axis_names == (hvd.AXIS,)
    assert m.devices.size == 8


def test_build_flags():
    assert hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()
    assert not hvd.mpi_threads_supported()


def test_worker_index_in_graph():
    from jax.sharding import PartitionSpec as P
    from horovod_tpu import spmd

    out = spmd.run(
        lambda: hvd.worker_index()[None],
        in_specs=(),
        out_specs=P(hvd.AXIS),
    )
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))


def test_not_initialized_error():
    # A fresh import-level call path raises before init; simulate by
    # temporarily clearing the context.
    ctx = basics._context
    basics._context = None
    try:
        with pytest.raises(basics.NotInitializedError):
            hvd.size()
    finally:
        basics._context = ctx
