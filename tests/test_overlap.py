"""Overlapped decode pipeline (EngineConfig.overlap) A/B oracle suite.

THE acceptance check for the pipelined engine: with ``overlap=True``
(device-resident token loop, one-tick-lag retirement, batched prefill)
every request's greedy output is TOKEN-IDENTICAL to the synchronous
path (``overlap=False``) and to per-request ``greedy_decode`` — across
staggered admissions, EOS / length retirement, cancellation, and
supervised restart — while the decode executable still never
recompiles and the batched-prefill compile set stays bounded by
buckets x max_prefills_per_tick.

The ``perf``-marked test is the hot-path regression guard: steady-state
overlapped decode performs at most ONE host sync per dispatched tick
(the deferred fetch of the previous tick) — an accidental
``np.asarray`` / ``block_until_ready`` creeping back onto the hot path
shows up as a ratio above 1.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T

pytestmark = pytest.mark.serving


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _engine(model, overlap, **kw):
    params, cfg = model
    defaults = dict(n_slots=4, max_len=40, min_prefill_bucket=4,
                    max_prefills_per_tick=2, max_queue_depth=16,
                    restart_backoff=0.01, restart_backoff_max=0.05,
                    overlap=overlap)
    defaults.update(kw)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults))


def _run_until_done(engine, futs, max_ticks=400):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


# Mixed workload exercised identically in both modes: unequal prompt
# lengths (two buckets), unequal completion lengths (slot reuse), an
# explicit EOS stop, and more requests than slots.
_CASES = [
    ([3, 4, 5, 6], 9, None),
    ([10, 11], 5, None),
    ([7, 8, 9, 1, 2, 3, 4, 5, 6], 7, None),  # second bucket
    ([12, 13, 14], 11, None),
    ([5, 6], 4, None),
    ([20, 21, 22], 12, "eos"),  # eos_id patched to a really-emitted token
]


class TestOverlapOracle:
    @pytest.mark.slow
    def test_ab_token_identity_staggered(self, model):
        """ACCEPTANCE: the same staggered workload through overlap=True
        and overlap=False produces identical token streams, both equal
        to per-request greedy_decode; EOS and length retirements land
        identically; decode never recompiles in either mode."""
        params, cfg = model
        # Resolve the EOS case against the oracle first: stop at a
        # token greedy really emits mid-stream.
        cases = []
        for prompt, steps, kind in _CASES:
            ref = _ref_greedy(params, cfg, prompt, steps)
            eos = ref[2] if kind == "eos" else None
            cases.append((prompt, steps, eos, ref))

        outs = {}
        for overlap in (True, False):
            engine = _engine(model, overlap)
            futs = []
            for prompt, steps, eos, _ in cases:
                futs.append(engine.submit(prompt, max_new_tokens=steps,
                                          eos_id=eos))
                engine.step()  # staggered: admissions land mid-decode
                engine.step()
            _run_until_done(engine, futs)
            assert engine.decode_compilations == 1
            outs[overlap] = [(f.result(timeout=0), f.finish_reason)
                             for f in futs]

        assert outs[True] == outs[False]  # the A/B identity
        for (prompt, steps, eos, ref), (toks, reason) in zip(
                cases, outs[True]):
            if eos is None:
                assert toks == ref
                assert reason == "length"
            else:
                assert toks == ref[:ref.index(eos) + 1]
                assert reason == "eos"

    @pytest.mark.slow
    def test_ab_with_cancellation(self, model):
        """Mid-stream cancellation at the same emission point in both
        modes: the cancelled future resolves with the same partial
        tokens, and the reused slot's later output stays
        oracle-exact."""
        params, cfg = model
        outs = {}
        for overlap in (True, False):
            engine = _engine(model, overlap, n_slots=2)
            victim = engine.submit([9, 8, 7], max_new_tokens=30)
            other = engine.submit([3, 4], max_new_tokens=8)
            while len(victim.tokens_so_far()) < 3:
                engine.step()
            n_at_cancel = len(victim.tokens_so_far())
            assert victim.cancel() is True
            late = engine.submit([5, 6, 7, 8], max_new_tokens=6)
            _run_until_done(engine, [victim, other, late])
            assert victim.finish_reason == "cancelled"
            outs[overlap] = (victim.result(timeout=0)[:n_at_cancel],
                             other.result(timeout=0),
                             late.result(timeout=0))
        assert outs[True][0] == outs[False][0][:len(outs[True][0])]
        assert outs[True][1] == outs[False][1] == _ref_greedy(
            params, cfg, [3, 4], 8)
        assert outs[True][2] == outs[False][2] == _ref_greedy(
            params, cfg, [5, 6, 7, 8], 6)

    @pytest.mark.slow
    def test_ab_across_restart(self, model):
        """Slow (PR 17 budget pass): both-modes restart pair is
        ~10 s; test_chaos's TestRestartResume keeps crash-resume
        oracle-exactness tier-1 (overlap mode) and the sync-mode
        restart rides the legacy test below's sibling set.

        A mid-decode device fault in each mode: the in-flight
        request RESUMES across the restart (journaled decode state,
        same future) and its output is oracle-exact in both modes —
        the pipeline state (device tokens, in-flight tick) is rebuilt
        from scratch, and the one-tick-lag identity snapshot keeps the
        overlapped path's journal identical to the sync path's."""
        params, cfg = model
        for overlap in (True, False):
            inj = serving.FaultInjector([
                serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=2)])
            engine = _engine(model, overlap, faults=inj)
            survivor = engine.submit([1, 2, 3], max_new_tokens=10)
            _run_until_done(engine, [survivor])
            assert survivor.result(timeout=0) == _ref_greedy(
                params, cfg, [1, 2, 3], 10)
            fut = engine.submit([1, 2, 3], max_new_tokens=10)
            _run_until_done(engine, [fut])
            assert fut.result(timeout=0) == _ref_greedy(
                params, cfg, [1, 2, 3], 10)
            s = engine.stats()
            assert s["engine_restarts"] == 1
            assert s["requests_resumed"] == 1
            # restarts swap the cache, never the compiled tick
            assert engine.decode_compilations == 1

    @pytest.mark.slow
    def test_ab_across_restart_legacy_fail_typed(self, model):
        """resume=False (the pre-journal contract): the in-flight
        batch fails typed in both modes, and post-restart output is
        oracle-exact.  Slow (PR 17 budget pass): ~7 s; test_chaos's
        typed-failure tests keep the resume=False contract tier-1."""
        params, cfg = model
        for overlap in (True, False):
            inj = serving.FaultInjector([
                serving.FaultSpec(site="decode_tick", kind="raise",
                                  skip=2)])
            engine = _engine(model, overlap, faults=inj, resume=False)
            doomed = engine.submit([1, 2, 3], max_new_tokens=10)
            _run_until_done(engine, [doomed])
            with pytest.raises(serving.EngineFailedError):
                doomed.result(timeout=0)
            fut = engine.submit([1, 2, 3], max_new_tokens=10)
            _run_until_done(engine, [fut])
            assert fut.result(timeout=0) == _ref_greedy(
                params, cfg, [1, 2, 3], 10)
            assert engine.stats()["engine_restarts"] == 1
            assert engine.decode_compilations == 1

    def test_prefill_compile_set_bounded(self, model):
        """Batched admission compiles per (bucket, k) pair and nothing
        else: a workload over two buckets with K=2 admissions per tick
        stays within buckets x K compilations, asserted via the
        engine's prefill trace hook."""
        params, cfg = model
        engine = _engine(model, True)
        rng = np.random.default_rng(3)
        futs = []
        for n in (3, 4, 2, 3, 7, 8, 6, 5, 4, 2):  # buckets {4, 8}
            p = rng.integers(0, cfg.vocab_size, n).tolist()
            futs.append(engine.submit(p, max_new_tokens=4))
        _run_until_done(engine, futs)
        for f in futs:
            assert len(f.result(timeout=0)) == 4
        s = engine.stats()
        n_buckets = len({b for b, _ in s["prefill_buckets"]})
        assert n_buckets == 2
        k = engine.engine_cfg.max_prefills_per_tick
        assert s["prefill_compilations"] <= n_buckets * k
        assert s["decode_compilations"] == 1


@pytest.mark.perf
class TestHotPathRegression:
    def test_steady_state_single_host_sync_per_tick(self, model):
        """REGRESSION GUARD: with overlap on, the steady-state decode
        loop (no admissions, no retirements) performs exactly one host
        sync per dispatched tick — the deferred fetch.  A reintroduced
        np.asarray / block_until_ready on the hot path pushes the
        ratio above 1."""
        engine = _engine(model, True, n_slots=2)
        fut = engine.submit([2, 3, 4], max_new_tokens=38)
        for _ in range(6):  # admission + pipeline fill + warmup
            engine.step()
        assert not fut.done()
        syncs0 = engine.metrics.host_syncs.value
        ticks0 = engine.metrics.decode_ticks.value
        n = 12
        for _ in range(n):
            engine.step()
        assert not fut.done()  # still steady-state (no retirement)
        dsync = engine.metrics.host_syncs.value - syncs0
        dtick = engine.metrics.decode_ticks.value - ticks0
        assert dtick == n
        assert dsync <= dtick  # <= 1 host sync per tick
        # and the global ratio /stats exports stays sane
        assert engine.stats()["host_syncs_per_tick"] is not None
        _run_until_done(engine, [fut])

    def test_sync_mode_counts_one_sync_per_tick_too(self, model):
        """The counter itself is mode-agnostic: the synchronous path's
        in-step fetch also counts exactly one sync per tick, so the
        A/B benchmark's host_syncs_per_tick numbers are comparable."""
        engine = _engine(model, False, n_slots=2)
        fut = engine.submit([2, 3, 4], max_new_tokens=20)
        engine.step()
        syncs0 = engine.metrics.host_syncs.value
        ticks0 = engine.metrics.decode_ticks.value
        for _ in range(8):
            engine.step()
        assert (engine.metrics.host_syncs.value - syncs0
                == engine.metrics.decode_ticks.value - ticks0 == 8)
        _run_until_done(engine, [fut])

    def test_phase_timers_populate(self, model):
        """The tick-phase histograms (dispatch / device-wait / host)
        fill for both modes and survive the /stats snapshot."""
        for overlap in (True, False):
            engine = _engine(model, overlap, n_slots=2)
            fut = engine.submit([1, 2], max_new_tokens=6)
            _run_until_done(engine, [fut])
            s = engine.stats()
            for key in ("tick_dispatch_seconds",
                        "tick_device_wait_seconds", "tick_host_seconds"):
                assert s[key]["count"] > 0, (overlap, key)
            assert s["decode_ticks"] > 0
            assert s["host_syncs"] > 0
