"""Model zoo + GSPMD multi-axis sharding tests (the dryrun_multichip path:
dp/tp/sp/pp/ep over the 8-device test mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import spmd
from horovod_tpu.models import mlp, transformer as T
from horovod_tpu.parallel.meshes import MeshSpec, infer_spec, make_mesh


class TestMLP:
    def test_forward_and_loss(self):
        params = mlp.init_params(jax.random.PRNGKey(0), (16, 8, 4))
        x = np.random.randn(5, 16).astype(np.float32)
        y = np.random.randint(0, 4, (5,))
        logits = mlp.forward(params, x)
        assert logits.shape == (5, 4)
        loss = mlp.loss_fn(params, (x, y))
        assert np.isfinite(float(loss))
        acc = mlp.accuracy(params, (x, y))
        assert 0.0 <= float(acc) <= 1.0

    def test_trains_with_distributed_optimizer(self):
        params = mlp.init_params(jax.random.PRNGKey(0), (8, 16, 2))
        rng = np.random.RandomState(0)
        x = rng.randn(64, 8).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int32)
        opt = hvd.DistributedOptimizer(optax.adam(0.01))
        step = spmd.make_train_step(mlp.loss_fn, opt)
        st = opt.init(params)
        losses = []
        for _ in range(40):
            params, st, loss = step(params, st, (x, y))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5


class TestTransformer:
    CFG = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=16
    )

    def test_forward_shapes(self):
        params = T.init_params(jax.random.PRNGKey(0), self.CFG)
        batch = T.synthetic_batch(0, self.CFG, batch=2)
        logits = T.forward(params, batch["tokens"], self.CFG)
        assert logits.shape == (2, 16, 64)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        params = T.init_params(jax.random.PRNGKey(0), self.CFG)
        batch = T.synthetic_batch(0, self.CFG, batch=1)
        toks = np.asarray(batch["tokens"]).copy()
        l1 = np.asarray(T.forward(params, jnp.asarray(toks), self.CFG))
        toks2 = toks.copy()
        toks2[0, -1] = (toks2[0, -1] + 1) % 64
        l2 = np.asarray(T.forward(params, jnp.asarray(toks2), self.CFG))
        np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-4)
        assert np.abs(l1[0, -1] - l2[0, -1]).max() > 1e-6

    def test_loss_finite_and_decreases(self):
        cfg = self.CFG
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(0, cfg, batch=4)
        opt = optax.adam(1e-2)
        st = opt.init(params)

        @jax.jit
        def step(p, s, b):
            loss, g = jax.value_and_grad(lambda p: T.loss_fn(p, b, cfg))(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        losses = []
        for _ in range(15):
            params, st, loss = step(params, st, batch)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_remat_matches_no_remat(self):
        """jax.checkpoint must change memory, not math: loss AND
        gradients identical with and without layer rematerialization."""
        import dataclasses

        params = T.init_params(jax.random.PRNGKey(0), self.CFG)
        batch = T.synthetic_batch(0, self.CFG, batch=2)
        l0, g0 = jax.value_and_grad(lambda p: T.loss_fn(p, batch, self.CFG))(params)
        for policy in ("full", "dots"):
            cfg_r = dataclasses.replace(self.CFG, remat=True,
                                        remat_policy=policy)
            l1, g1 = jax.value_and_grad(
                lambda p: T.loss_fn(p, batch, cfg_r))(params)
            assert jnp.allclose(l0, l1, atol=1e-6)
            for a, b in zip(jax.tree_util.tree_leaves(g0),
                            jax.tree_util.tree_leaves(g1)):
                assert jnp.allclose(a, b, atol=1e-5), (policy, (a - b).max())

    def test_moe_forward(self):
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, n_experts=4,
        )
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        assert "router" in params["layers"]
        batch = T.synthetic_batch(0, cfg, batch=2)
        logits = T.forward(params, batch["tokens"], cfg)
        assert np.isfinite(np.asarray(logits)).all()


class TestDecode:
    """KV-cache autoregressive decoding: teacher-forcing equivalence with
    forward() is the gold check (same math, incremental evaluation)."""

    def _cfg(self, **kw):
        import dataclasses

        base = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, dtype=jnp.float32, attention_impl="reference")
        return dataclasses.replace(base, **kw)

    @pytest.mark.parametrize("kv_heads", [0, 2])
    def test_decode_matches_forward(self, kv_heads):
        cfg = self._cfg(n_kv_heads=kv_heads)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        full = T.forward(params, tokens, cfg)  # (2, 10, 64)

        cache = T.init_cache(cfg, batch=2, max_len=10)
        step = jax.jit(lambda t, c: T.decode_step(params, t, c, cfg))
        for t in range(10):
            logits, cache = step(tokens[:, t], cache)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, t]),
                atol=2e-4, rtol=2e-4)
        assert int(cache["pos"]) == 10

    @pytest.mark.slow
    def test_decode_moe(self):
        # capacity_factor >= n_experts makes switch dispatch dropless, so
        # forward (switch) vs decode (forced dense) teacher-forcing
        # equivalence holds EXACTLY — the documented serving contract
        # (_mlp_block docstring); with drops they legitimately diverge
        # (tests/test_moe.py covers that case).
        cfg = self._cfg(n_experts=2, capacity_factor=2.0)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 64)
        full = T.forward(params, tokens, cfg)
        cache = T.init_cache(cfg, batch=1, max_len=6)
        for t in range(6):
            logits, cache = T.decode_step(params, tokens[:, t], cache, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, t]),
                atol=2e-4, rtol=2e-4)

    def test_greedy_decode_matches_naive(self):
        """greedy_decode == repeatedly argmaxing forward() on the grown
        sequence (the cache must be a pure optimization)."""
        cfg = self._cfg()
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        steps = 5
        out = jax.jit(
            lambda p, pr: T.greedy_decode(p, pr, steps, cfg))(params, prompt)
        assert out.shape == (2, steps)

        seq = np.asarray(prompt)
        for _ in range(steps):
            logits = T.forward(params, jnp.asarray(seq), cfg)
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))[:, None]
            seq = np.concatenate([seq, nxt], axis=1)
        np.testing.assert_array_equal(np.asarray(out), seq[:, 4:])

    @pytest.mark.parametrize("kv_heads", [0, 2])
    def test_prefill_then_decode_matches_forward(self, kv_heads):
        """prefill fills the cache in one pass; subsequent decode steps
        must continue exactly where forward() would."""
        cfg = self._cfg(n_kv_heads=kv_heads)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        full = T.forward(params, tokens, cfg)

        cache = T.init_cache(cfg, batch=2, max_len=10)
        logits, cache = T.prefill(params, tokens[:, :6], cache, cfg)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, 5]),
                                   atol=2e-4, rtol=2e-4)
        assert int(cache["pos"]) == 6
        for t in range(6, 10):
            logits, cache = T.decode_step(params, tokens[:, t], cache, cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(full[:, t]),
                atol=2e-4, rtol=2e-4)

    def test_tp_sharded_decode_token_identical(self):
        """tp-sharded serving (params per serving_param_specs, KV cache
        head-sharded per cache_specs) must produce token-identical greedy
        output to single-chip decode, and the compiled step must actually
        shard the math (tp collectives in the HLO) — so a model that
        needed tp>1 to train can be served by this framework."""
        from jax.sharding import Mesh

        cfg = self._cfg(n_kv_heads=2)
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        steps = 5
        ref = T.greedy_decode(params, prompt, steps, cfg)

        tp = 2
        mesh = Mesh(np.array(jax.devices()[:tp]), axis_names=("tp",))
        param_sh, cache_sh = T.serving_shardings(mesh, cfg)
        params_tp = jax.device_put(params, param_sh)
        fn = jax.jit(lambda p, t: T.greedy_decode(
            p, t, steps, cfg, cache_shardings=cache_sh))
        hlo = fn.lower(params_tp, prompt).compile().as_text()
        assert "all-reduce" in hlo or "all-gather" in hlo, (
            "tp decode must emit tp collectives")
        out = fn(params_tp, prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    @pytest.mark.slow
    def test_checkpoint_to_tp_serving_roundtrip(self, tmp_path):
        """The full big-model lifecycle: train under a tp-sharded GSPMD
        step, checkpoint, restore from disk, and serve BOTH single-chip
        and tp-sharded — token-identical.  Proves checkpoints cross the
        training<->serving sharding boundary (GSPMD shardings are
        placement, not data layout)."""
        import optax
        from jax.sharding import Mesh

        from horovod_tpu import checkpoint

        cfg = self._cfg(n_kv_heads=2)
        params0 = T.init_params(jax.random.PRNGKey(0), cfg)
        mesh = Mesh(np.array(jax.devices()[:2]), axis_names=("tp",))
        param_sh, cache_sh = T.serving_shardings(mesh, cfg)
        params = jax.device_put(params0, param_sh)  # tp-sharded TRAINING
        batch = T.synthetic_batch(0, cfg, batch=4)
        opt = optax.sgd(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def train_step(params, opt_state):
            loss, g = jax.value_and_grad(
                lambda p: T.loss_fn(p, batch, cfg))(params)
            u, opt_state = opt.update(g, opt_state, params)
            return optax.apply_updates(params, u), opt_state, loss

        for _ in range(3):
            params, opt_state, loss = train_step(params, opt_state)
        assert np.isfinite(float(loss))

        checkpoint.save(str(tmp_path / "ckpt"), {"params": params})
        restored = checkpoint.restore(
            str(tmp_path / "ckpt"),
            {"params": T.init_params(jax.random.PRNGKey(9), cfg)})
        rp = restored["params"]
        # Training actually changed the weights, and the restore got THEM
        # (not the template's).
        assert not np.allclose(np.asarray(rp["head"]),
                               np.asarray(params0["head"]))
        np.testing.assert_allclose(np.asarray(rp["head"]),
                                   np.asarray(params["head"]), atol=0)

        # Sharding-aware restore: a SHARDED template places shards
        # directly on the serving mesh (no whole-tree bounce through one
        # device).
        restored_tp = checkpoint.restore(
            str(tmp_path / "ckpt"),
            {"params": jax.device_put(
                T.init_params(jax.random.PRNGKey(9), cfg), param_sh)})
        assert restored_tp["params"]["head"].sharding == param_sh["head"]
        np.testing.assert_allclose(np.asarray(restored_tp["params"]["head"]),
                                   np.asarray(rp["head"]), atol=0)

        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        ref = T.greedy_decode(rp, prompt, 5, cfg)  # single-chip serving
        rp_tp = jax.device_put(rp, param_sh)       # tp-sharded serving
        out = jax.jit(lambda p, t: T.greedy_decode(
            p, t, 5, cfg, cache_shardings=cache_sh))(rp_tp, prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_prefill_requires_fresh_cache(self):
        cfg = self._cfg()
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        cache = T.init_cache(cfg, batch=1, max_len=8)
        toks = jnp.zeros((1, 2), jnp.int32)
        _, cache = T.prefill(params, toks, cache, cfg)
        with pytest.raises(ValueError, match="fresh"):
            T.prefill(params, toks, cache, cfg)

    def test_prefill_capacity_checked(self):
        cfg = self._cfg()
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        cache = T.init_cache(cfg, batch=1, max_len=4)
        with pytest.raises(ValueError, match="larger max_len"):
            T.prefill(params, jnp.zeros((1, 6), jnp.int32), cache, cfg)

    @pytest.mark.slow
    def test_sample_decode_temperature_zero_is_greedy(self):
        # Slow (PR 17 budget pass): compiles both decode paths, ~7 s;
        # test_sampling keeps the temperature-0 == greedy property
        # tier-1 at the engine level.
        cfg = self._cfg()
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 4), 0, 64)
        greedy = T.greedy_decode(params, prompt, 4, cfg)
        sampled = T.sample_decode(params, prompt, 4, cfg,
                                  rng=jax.random.PRNGKey(9),
                                  temperature=0.0)
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.asarray(sampled))
        # top-k sampling stays within vocab and is deterministic per key
        s1 = T.sample_decode(params, prompt, 4, cfg,
                             rng=jax.random.PRNGKey(3), temperature=1.0,
                             top_k=4)
        s2 = T.sample_decode(params, prompt, 4, cfg,
                             rng=jax.random.PRNGKey(3), temperature=1.0,
                             top_k=4)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        assert np.asarray(s1).max() < 64 and np.asarray(s1).min() >= 0

    def test_gqa_cache_is_smaller(self):
        big = T.init_cache(self._cfg(), batch=1)
        small = T.init_cache(self._cfg(n_kv_heads=1), batch=1)
        assert small["k"].size * 4 == big["k"].size


class TestInception:
    @pytest.mark.slow
    def test_forward_and_grad(self):
        """InceptionV3 at a reduced-but-valid resolution: output shape,
        finite loss, gradients flow to every parameter."""
        from horovod_tpu.models import inception

        model = inception.create("InceptionV3", num_classes=10)
        variables = inception.init_variables(
            model, jax.random.PRNGKey(0), image_size=75, batch=2)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 75, 75, 3))
        logits, _ = model.apply(variables, x, train=True,
                                mutable=["batch_stats"])
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

        def loss(p):
            out, _ = model.apply(
                {"params": p, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return (out ** 2).mean()

        grads = jax.grad(loss)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(jnp.all(jnp.isfinite(l)) for l in leaves)
        assert any(float(jnp.abs(l).max()) > 0 for l in leaves)


@pytest.mark.slow
class TestGSPMDShardedStep:
    def test_dp_tp_sp_step(self):
        """Full train step over a (dp=2, sp=2, tp=2) mesh with real
        parameter/activation shardings — the dryrun_multichip path."""
        spec = infer_spec(8, tp=2, sp=2)
        mesh = make_mesh(spec)
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, n_experts=2,
        )
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        batch = T.synthetic_batch(0, cfg, batch=4, seq=16)
        opt = optax.sgd(1e-2)
        step = spmd.make_gspmd_train_step(
            lambda p, b: T.loss_fn(p, b, cfg),
            opt,
            mesh=mesh,
            param_spec=T.param_specs(cfg),
            batch_spec=T.batch_specs(),
            donate=False,
        )
        p2, _, loss = step(params, opt.init(params), batch)
        assert np.isfinite(float(loss))
        # sharded params actually changed
        d = np.abs(np.asarray(p2["embed"]) - np.asarray(params["embed"])).max()
        assert d > 0

    def test_sharded_matches_unsharded(self):
        """The GSPMD-sharded step computes the same numbers as a plain
        single-device step (collective insertion is semantics-preserving)."""
        spec = infer_spec(8, tp=2, sp=2)
        mesh = make_mesh(spec)
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=16
        )
        params = T.init_params(jax.random.PRNGKey(1), cfg)
        batch = T.synthetic_batch(1, cfg, batch=4, seq=16)
        opt = optax.sgd(1e-1)
        step = spmd.make_gspmd_train_step(
            lambda p, b: T.loss_fn(p, b, cfg),
            opt,
            mesh=mesh,
            param_spec=T.param_specs(cfg),
            batch_spec=T.batch_specs(),
            donate=False,
        )
        p_sharded, _, loss_sharded = step(params, opt.init(params), batch)

        loss_ref, g = jax.value_and_grad(lambda p: T.loss_fn(p, batch, cfg))(params)
        u, _ = opt.update(g, opt.init(params), params)
        p_ref = optax.apply_updates(params, u)
        np.testing.assert_allclose(
            float(loss_sharded), float(loss_ref), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(p_sharded["head"]), np.asarray(p_ref["head"]),
            rtol=5e-3, atol=1e-4,
        )

    def test_mesh_spec_validation(self):
        with pytest.raises(ValueError):
            infer_spec(8, tp=3)
        with pytest.raises(ValueError):
            make_mesh(MeshSpec(dp=16))

    @staticmethod
    def _bytes_per_device(*trees):
        """Device-0 resident bytes across the pytrees (every device holds
        the same amount under these uniform shardings)."""
        total = 0
        for tree in trees:
            for leaf in jax.tree_util.tree_leaves(tree):
                if isinstance(leaf, jax.Array) and leaf.addressable_shards:
                    total += leaf.addressable_shards[0].data.nbytes
        return total

    def _fsdp_step(self, fsdp):
        """One adam GSPMD step on a tp=2 mesh with the remaining factor
        split dp/fsdp; returns (loss, new_params, new_opt_state)."""
        spec = infer_spec(8, tp=2, fsdp=fsdp)
        mesh = make_mesh(spec)
        cfg = T.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=16, dtype=jnp.float32,
        )
        params = T.init_params(jax.random.PRNGKey(1), cfg)
        batch = T.synthetic_batch(1, cfg, batch=8, seq=16)
        opt = optax.adam(1e-2)  # moments double the state the ZeRO-3
        # claim covers (params + optimizer state both shard over fsdp)
        step = spmd.make_gspmd_train_step(
            lambda p, b: T.loss_fn(p, b, cfg),
            opt,
            mesh=mesh,
            param_spec=T.param_specs(cfg),
            batch_spec=T.batch_specs(),
            donate=False,
        )
        p2, o2, loss = step(params, opt.init(params), batch)
        jax.block_until_ready(p2)
        return cfg, params, batch, loss, p2, o2

    def test_fsdp_matches_unsharded(self):
        """fsdp=2: loss and updated params exactly track the plain
        single-device step — the axis is semantics-preserving, not just
        declared (round-4 verdict weak #1)."""
        cfg, params, batch, loss_f, p2, _ = self._fsdp_step(2)
        loss_ref, g = jax.value_and_grad(
            lambda p: T.loss_fn(p, batch, cfg))(params)
        opt = optax.adam(1e-2)
        u, _ = opt.update(g, opt.init(params), params)
        p_ref = optax.apply_updates(params, u)
        np.testing.assert_allclose(float(loss_f), float(loss_ref), rtol=1e-4)
        for k in ("head", "embed"):
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(p_ref[k]),
                rtol=5e-3, atol=1e-4, err_msg=k)

    def test_fsdp_shards_param_and_optimizer_memory(self):
        """The ZeRO-3 claim measured: per-device parameter + optimizer
        bytes at fsdp=2 are ~half of the fsdp=1 run on the same-size
        mesh (both tp=2; dp picks up the leftover)."""
        *_, p1, o1 = self._fsdp_step(1)
        *_, p2, o2 = self._fsdp_step(2)
        b1 = self._bytes_per_device(p1, o1)
        b2 = self._bytes_per_device(p2, o2)
        # fsdp=2 halves every fsdp-sharded leaf; small replicated leaves
        # (norm scales) keep the ratio just above 0.5.
        assert b2 < 0.6 * b1, (b1, b2)
        assert b2 > 0.4 * b1, (b1, b2)


class TestGraftEntry:
    def test_entry_compiles(self):
        import importlib.util, pathlib

        spec = importlib.util.spec_from_file_location(
            "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.slow
    def test_dryrun_multichip(self, capsys):
        import importlib.util, pathlib

        spec = importlib.util.spec_from_file_location(
            "graft_entry2", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)
        assert "dryrun_multichip OK" in capsys.readouterr().out
