"""State broadcast, object collectives, Join, and elastic State tests
(reference: test_torch.py test_broadcast_state:911, broadcast_object,
test_horovod_join_allreduce:1540)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import elastic, spmd
from horovod_tpu.join import masked_average

N = 8


class TestBroadcastState:
    def test_broadcast_parameters_eager(self):
        params = {"w": np.random.randn(3, 2).astype(np.float32)}
        out = hvd.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(out["w"], params["w"])

    def test_broadcast_parameters_in_graph(self):
        x = np.random.RandomState(0).randn(N, 4).astype(np.float32)

        def inner(t):
            return hvd.broadcast_parameters({"w": t[0]}, root_rank=2)["w"][None]

        out = jax.jit(
            spmd.shard(inner, in_specs=(P(hvd.AXIS),), out_specs=P(hvd.AXIS))
        )(x)
        for i in range(N):
            np.testing.assert_allclose(np.asarray(out)[i], x[2])

    def test_broadcast_optimizer_state(self):
        opt = optax.adam(1e-3)
        params = {"w": jnp.ones((3,))}
        st = opt.init(params)
        out = hvd.broadcast_optimizer_state(st, root_rank=0)
        # structure preserved and numerically identical (single process)
        l1 = jax.tree_util.tree_leaves(st)
        l2 = jax.tree_util.tree_leaves(out)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_broadcast_object(self):
        obj = {"lr": 0.1, "sched": [1, 2, 3], "name": "resnet"}
        assert hvd.broadcast_object(obj, 0) == obj

    def test_allgather_object(self):
        out = hvd.allgather_object({"r": 0})
        assert out == [{"r": 0}]


class TestJoin:
    def test_masked_average_all_active(self):
        x = np.random.RandomState(0).randn(N, 4).astype(np.float32)
        act = np.ones((N, 1), np.float32)

        def inner(t, a):
            return masked_average(t[0], a[0, 0])[None]

        out = jax.jit(
            spmd.shard(
                inner,
                in_specs=(P(hvd.AXIS), P(hvd.AXIS)),
                out_specs=P(hvd.AXIS),
            )
        )(x, act)
        np.testing.assert_allclose(np.asarray(out)[0], x.mean(0), rtol=1e-5)

    def test_masked_average_some_joined(self):
        """Joined (inactive) workers contribute zeros and shrink the
        divisor — controller.cc:780-803 ready-count semantics."""
        x = np.random.RandomState(1).randn(N, 4).astype(np.float32)
        act = np.ones((N, 1), np.float32)
        act[5:] = 0.0  # workers 5,6,7 have joined

        def inner(t, a):
            return masked_average(t[0], a[0, 0])[None]

        out = jax.jit(
            spmd.shard(
                inner,
                in_specs=(P(hvd.AXIS), P(hvd.AXIS)),
                out_specs=P(hvd.AXIS),
            )
        )(x, act)
        expect = x[:5].mean(0)
        for i in range(N):
            np.testing.assert_allclose(np.asarray(out)[i], expect, rtol=1e-4)

    def test_masked_average_all_joined_no_nan(self):
        x = np.ones((N, 3), np.float32)
        act = np.zeros((N, 1), np.float32)

        def inner(t, a):
            return masked_average(t[0], a[0, 0])[None]

        out = jax.jit(
            spmd.shard(
                inner,
                in_specs=(P(hvd.AXIS), P(hvd.AXIS)),
                out_specs=P(hvd.AXIS),
            )
        )(x, act)
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_eager_join_returns_last_rank(self):
        assert hvd.join() == hvd.rank()


class TestElasticState:
    def test_sync_and_checkpoint_roundtrip(self, tmp_path):
        params = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)}
        st = elastic.State(params=params, epoch=3, meta={"run": "x"})
        st.sync()
        path = str(tmp_path / "ckpt.pkl")
        st.save(path)

        st2 = elastic.State(params={"w": jnp.zeros((2, 3))}, epoch=0, meta={})
        assert st2.restore(path)
        np.testing.assert_allclose(np.asarray(st2.params["w"]), np.asarray(params["w"]))
        assert st2.epoch == 3
        assert st2.meta == {"run": "x"}

    def test_restore_missing(self, tmp_path):
        st = elastic.State(params={"w": jnp.zeros(2)})
        assert not st.restore(str(tmp_path / "nope.pkl"))

    def test_elastic_run_decorator(self):
        st = elastic.State(x=1)

        @elastic.run
        def train(state):
            return state.x + 1

        assert train(st) == 2


class TestElasticCommitRollback:
    def test_rollback_restores_last_commit(self):
        st = elastic.State(params={"w": np.arange(4, dtype=np.float32)}, step=0)
        st.params["w"] = st.params["w"] + 1.0
        st.step = 5
        st.commit()
        st.params["w"] = st.params["w"] * 100.0  # uncommitted wreckage
        st.step = 6
        st.rollback()
        assert st.step == 5
        np.testing.assert_allclose(st.params["w"],
                                   np.arange(4, dtype=np.float32) + 1.0)

    def test_rollback_before_commit_restores_init(self):
        st = elastic.State(x=[1, 2], step=0)
        st.x.append(3)
        st.step = 9
        st.rollback()
        assert st.x == [1, 2] and st.step == 0

    def test_snapshot_survives_donated_buffers(self):
        """make_train_step donates its input buffers by default; the
        committed snapshot must hold its own copies, not references that
        the next step deletes."""
        w = jnp.arange(4, dtype=jnp.float32)
        st = elastic.State(params={"w": w}, step=0)
        st.commit()
        w.delete()  # what donation does to the committed reference
        st.rollback()
        np.testing.assert_allclose(np.asarray(st.params["w"]),
                                   [0.0, 1.0, 2.0, 3.0])

    def test_commit_also_writes_durable_checkpoint(self, tmp_path):
        path = str(tmp_path / "st.pkl")
        st = elastic.State(step=7)
        st.commit(path)
        st2 = elastic.State(step=0)
        assert st2.restore(path) and st2.step == 7

    def test_hosts_updated_interrupt_at_commit_boundary(self):
        st = elastic.State(step=1)
        st.on_hosts_updated()
        with pytest.raises(elastic.HostsUpdatedInterrupt):
            st.commit()
        st.commit()  # one-shot: cleared after raising
        st.rollback()
        assert st.step == 1  # the interrupting commit still snapshotted

    def test_run_replays_uncommitted_step_after_internal_error(self):
        """The elastic.run contract: a committed step is never lost, an
        uncommitted one is cleanly replayed after a collective failure."""
        st = elastic.State(acc=0.0, step=0)
        attempts = []

        @elastic.run
        def train(state):
            attempts.append(int(state.step))
            while state.step < 4:
                state.acc = float(state.acc) + 1.0
                state.step = int(state.step) + 1
                if state.step == 2:
                    state.commit()
                if state.step == 3 and len(attempts) == 1:
                    # uncommitted step 3 dies mid-collective
                    raise elastic.HorovodInternalError("peer died")
            return int(state.step)

        assert train(st) == 4
        assert attempts == [0, 2]  # replay resumed from the commit
        assert st.acc == 4.0  # step 3's first, discarded attempt not double-counted

    def test_run_resyncs_after_hosts_updated(self):
        st = elastic.State(step=0)
        seen = []

        @elastic.run
        def train(state):
            seen.append(int(state.step))
            while state.step < 3:
                state.step = int(state.step) + 1
                if state.step == 2 and len(seen) == 1:
                    state.on_hosts_updated()
                state.commit()  # boundary: interrupt surfaces here
            return int(state.step)

        assert train(st) == 3
        # second attempt resumed from the committed step 2 (no rollback
        # on a hosts-updated interrupt: the state is commit-consistent)
        assert seen == [0, 2]
