"""Adasum correctness: distributed (ppermute recursion) vs the closed-form
pairwise formula (reference: test/test_adasum_tensorflow.py and
test_adasum_pytorch.py check against the same math)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import spmd
from horovod_tpu.ops import adasum

N = 8


def _pairwise_np(a, b):
    dot = float(np.vdot(a.astype(np.float64), b.astype(np.float64)))
    asq = float(np.vdot(a.astype(np.float64), a.astype(np.float64)))
    bsq = float(np.vdot(b.astype(np.float64), b.astype(np.float64)))
    ac = 1.0 - dot / (2 * asq) if asq > 0 else 1.0
    bc = 1.0 - dot / (2 * bsq) if bsq > 0 else 1.0
    return ac * a + bc * b


def _reference_reduce(stack):
    x = [s for s in stack]
    while len(x) > 1:
        x = [_pairwise_np(x[i], x[i + 1]) for i in range(0, len(x), 2)]
    return x[0]


def _run_distributed(x):
    def inner(t):
        return hvd.allreduce(t[0], hvd.Adasum)[None]

    return np.asarray(
        jax.jit(
            spmd.shard(inner, in_specs=(P(hvd.AXIS),), out_specs=P(hvd.AXIS))
        )(x)
    )


class TestAdasumMath:
    def test_two_orthogonal(self):
        """Orthogonal gradients add (dot=0 → coefficients 1)."""
        a = np.array([1.0, 0.0], np.float32)
        b = np.array([0.0, 1.0], np.float32)
        out = _pairwise_np(a, b)
        np.testing.assert_allclose(out, [1.0, 1.0])

    def test_two_identical(self):
        """Identical gradients average (coefficients 1/2)."""
        a = np.array([2.0, 4.0], np.float32)
        out = _pairwise_np(a, a.copy())
        np.testing.assert_allclose(out, a)

    def test_stack_oracle_matches_serial(self):
        rng = np.random.RandomState(0)
        stack = rng.randn(4, 16).astype(np.float32)
        got = np.asarray(adasum.adasum_reduce_stack(stack))
        expect = _reference_reduce(stack)
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


class TestAdasumDistributed:
    def test_matches_oracle(self):
        rng = np.random.RandomState(1)
        x = rng.randn(N, 32).astype(np.float32)
        out = _run_distributed(x)
        expect = _reference_reduce(x)
        for i in range(N):
            np.testing.assert_allclose(out[i], expect, rtol=1e-3, atol=1e-4)

    def test_identical_grads_idempotent(self):
        g = np.random.RandomState(2).randn(16).astype(np.float32)
        x = np.tile(g, (N, 1))
        out = _run_distributed(x)
        np.testing.assert_allclose(out[0], g, rtol=1e-4, atol=1e-5)

    def test_zero_grads(self):
        x = np.zeros((N, 8), np.float32)
        out = _run_distributed(x)
        np.testing.assert_allclose(out, np.zeros((N, 8)))

    def test_scale_insensitivity(self):
        """Adasum of {g, g} is g regardless of |g| — the property that
        motivates the algorithm (adasum.h header comment)."""
        g = np.random.RandomState(3).randn(8).astype(np.float32)
        for scale in (1e-3, 1.0, 1e3):
            x = np.tile(g * scale, (N, 1))
            out = _run_distributed(x)
            np.testing.assert_allclose(out[0], g * scale, rtol=1e-3)

    def test_hierarchical(self):
        """(cross, local): local mean then Adasum across hosts
        (AdasumGpuAllreduce structure)."""
        hm = hvd.hierarchical_mesh()
        rng = np.random.RandomState(4)
        x = rng.randn(*hm.devices.shape, 16).astype(np.float32)

        def inner(t):
            return hvd.allreduce(
                t[0, 0], hvd.Adasum, axis_name=("cross", "local")
            )[None, None]

        out = np.asarray(
            jax.jit(
                spmd.shard(
                    inner,
                    in_specs=(P("cross", "local"),),
                    out_specs=P("cross", "local"),
                    mesh=hm,
                )
            )(x)
        )
        locals_mean = x.mean(axis=1)  # (cross, 16)
        expect = _reference_reduce(locals_mean)
        np.testing.assert_allclose(out[0, 0], expect, rtol=1e-3, atol=1e-4)

    def test_eager_single_process_identity(self):
        x = np.random.randn(8).astype(np.float32)
        out = hvd.allreduce(x, hvd.Adasum)
        np.testing.assert_allclose(out, x)
