"""Test fixture: 8 virtual CPU devices standing in for an 8-chip TPU slice.

The reference runs every test body under a 2-process mpirun/horovodrun
launcher (SURVEY.md §4).  Here the same multi-worker coverage comes from 8
virtual CPU devices — single process, real XLA collectives through the same
shard_map code paths that run on ICI.  Multi-process behavior is covered
separately by the launcher tests, which spawn real processes.

Note: this sandbox's sitecustomize imports jax at interpreter startup with
the TPU platform selected, so env vars (XLA_FLAGS/JAX_PLATFORMS) are too
late — we must use jax.config.update before any backend is touched.
"""

import os

# Must precede backend initialization: on JAX builds without the
# jax_num_cpu_devices config option the XLA flag is the only way to get
# virtual CPU devices, and it is read when the CPU backend spins up.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older JAX: XLA_FLAGS above does the job
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _hvd():
    import horovod_tpu as hvd

    hvd.init()
    yield hvd


@pytest.fixture()
def hvd(_hvd):
    return _hvd


def http_post_json(url, payload, timeout=60.0):
    """POST JSON to the serving server; returns (status, parsed body),
    unwrapping HTTPError so typed rejections (429/413/503/504) read
    like normal responses.  Shared by the serving and chaos suites so
    the response-protocol handling cannot silently diverge."""
    import json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def assert_compile_set(engine, *, decode=None, prefill=None, sample=None):
    """The compile-count guard: assert an engine has built EXACTLY the
    expected executables — no more, no fewer.  Shared by the paged /
    sched / tp suites so every zero-recompile assertion reads the same
    counters the /stats endpoint exposes (``decode_compilations`` etc.),
    and so the fused paged-kernel path proves it adds NEW executables
    (prefill + decode [+ verify]) rather than per-tick retraces: run
    traffic, snapshot, run more traffic, call again with the same
    expectations.  ``None`` skips a counter."""
    stats = engine.stats()
    got = {
        "decode": stats["decode_compilations"],
        "prefill": stats["prefill_compilations"],
        "sample": stats["sample_compilations"],
    }
    want = {"decode": decode, "prefill": prefill, "sample": sample}
    bad = {k: (got[k], want[k]) for k in got
           if want[k] is not None and got[k] != want[k]}
    assert not bad, (
        "compile-set mismatch (counter: got != expected): "
        + ", ".join(f"{k}: {g} != {w}" for k, (g, w) in bad.items()))
    return got


def parse_prometheus_text(text):
    """STRICT parser/validator for Prometheus text exposition (0.0.4);
    the golden check behind the /metrics tests (shared by test_obs.py
    and test_chaos.py so the format contract cannot silently diverge).

    Asserts the structural rules a real scraper relies on — every
    sample line parses, a sample's family has a preceding # TYPE,
    sample names match their family (histograms: _bucket/_sum/_count),
    histogram bucket counts are cumulative and the +Inf bucket equals
    _count — and returns {family: {"type": ..., "help": ...,
    "samples": [(name, labels_dict, value)]}}.
    """
    import re

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
        r"(?:\{([^}]*)\})?"                      # optional labels
        r" (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|\+Inf)$")
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    families = {}
    current = None
    for line in text.splitlines():
        assert line.strip() == line and line, f"bad line framing: {line!r}"
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            fam = rest.split(" ", 1)[0]
            assert name_re.match(fam), fam
            families.setdefault(fam, {"type": None, "help": None,
                                      "samples": []})
            families[fam]["help"] = rest.partition(" ")[2]
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"bad TYPE line: {line!r}"
            fam, kind = parts[2], parts[3]
            assert name_re.match(fam), fam
            assert kind in ("counter", "gauge", "histogram"), kind
            families.setdefault(fam, {"type": None, "help": None,
                                      "samples": []})
            families[fam]["type"] = kind
            current = fam
            continue
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, raw_labels, raw_value = m.groups()
        labels = dict(label_re.findall(raw_labels)) if raw_labels else {}
        value = float(raw_value.replace("+Inf", "inf"))
        # the sample must belong to the most recent TYPE'd family
        assert current is not None, f"sample before any TYPE: {line!r}"
        kind = families[current]["type"]
        if kind == "histogram":
            assert name in (current + "_bucket", current + "_sum",
                            current + "_count"), (name, current)
            if name.endswith("_bucket"):
                assert "le" in labels, line
        else:
            assert name == current, (name, current)
        families[current]["samples"].append((name, labels, value))
    # histogram invariants: buckets cumulative, +Inf == _count
    for fam, f in families.items():
        if f["type"] != "histogram":
            continue
        series = {}
        count = {}
        for name, labels, value in f["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            if name.endswith("_bucket"):
                series.setdefault(key, []).append(
                    (float(labels["le"].replace("+Inf", "inf")), value))
            elif name.endswith("_count"):
                count[key] = value
        for key, buckets in series.items():
            buckets.sort()
            values = [v for _, v in buckets]
            assert values == sorted(values), (fam, key, "not cumulative")
            assert buckets[-1][0] == float("inf"), (fam, key)
            assert buckets[-1][1] == count.get(key), (fam, key)
    return families
