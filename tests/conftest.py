"""Test fixture: 8 virtual CPU devices standing in for an 8-chip TPU slice.

The reference runs every test body under a 2-process mpirun/horovodrun
launcher (SURVEY.md §4).  Here the same multi-worker coverage comes from 8
virtual CPU devices — single process, real XLA collectives through the same
shard_map code paths that run on ICI.  Multi-process behavior is covered
separately by the launcher tests, which spawn real processes.

Note: this sandbox's sitecustomize imports jax at interpreter startup with
the TPU platform selected, so env vars (XLA_FLAGS/JAX_PLATFORMS) are too
late — we must use jax.config.update before any backend is touched.
"""

import os

# Must precede backend initialization: on JAX builds without the
# jax_num_cpu_devices config option the XLA flag is the only way to get
# virtual CPU devices, and it is read when the CPU backend spins up.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older JAX: XLA_FLAGS above does the job
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _hvd():
    import horovod_tpu as hvd

    hvd.init()
    yield hvd


@pytest.fixture()
def hvd(_hvd):
    return _hvd


def http_post_json(url, payload, timeout=60.0):
    """POST JSON to the serving server; returns (status, parsed body),
    unwrapping HTTPError so typed rejections (429/413/503/504) read
    like normal responses.  Shared by the serving and chaos suites so
    the response-protocol handling cannot silently diverge."""
    import json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
