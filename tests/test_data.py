"""Input pipeline: sharding/lockstep/shuffle/prefetch contract."""

import jax
import numpy as np
import pytest

import horovod_tpu as hvd  # noqa: F401
from horovod_tpu.data import DataLoader


def _arrays(n=100):
    return {"x": np.arange(n, dtype=np.float32).reshape(n, 1),
            "y": np.arange(n, dtype=np.float32)}


class TestDataLoader:
    def test_batches_on_device_and_complete(self, hvd):
        dl = DataLoader(_arrays(64), 8, shuffle=False, shard=False)
        batches = list(dl)
        assert len(batches) == len(dl) == 8
        assert all(isinstance(b["x"], jax.Array) for b in batches)
        seen = np.concatenate([np.asarray(b["y"]) for b in batches])
        np.testing.assert_array_equal(np.sort(seen), np.arange(64))

    def test_drop_remainder(self, hvd):
        dl = DataLoader(_arrays(70), 8, shuffle=False, shard=False)
        assert len(dl) == 8  # 70 // 8, last 6 rows dropped

    def test_epoch_reshuffle_deterministic(self, hvd):
        a = _arrays(32)
        dl1 = DataLoader(a, 8, shuffle=True, seed=5, shard=False)
        dl2 = DataLoader(a, 8, shuffle=True, seed=5, shard=False)
        e1 = [np.asarray(b["y"]) for b in dl1]
        e1b = [np.asarray(b["y"]) for b in dl1]  # second epoch differs
        e2 = [np.asarray(b["y"]) for b in dl2]
        np.testing.assert_array_equal(np.concatenate(e1),
                                      np.concatenate(e2))
        assert not np.array_equal(np.concatenate(e1), np.concatenate(e1b))

    def test_prefetch_zero_and_large(self, hvd):
        for prefetch in (0, 100):
            dl = DataLoader(_arrays(32), 8, shuffle=False, shard=False,
                            prefetch=prefetch)
            assert len(list(dl)) == 4

    def test_mesh_sharding_placement(self, hvd):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(hvd.mesh(), P(hvd.AXIS))
        dl = DataLoader(_arrays(64), 16, shuffle=False, shard=False,
                        sharding=sh)
        b = next(iter(dl))
        assert b["x"].sharding == sh

    def test_large_seed_ok(self, hvd):
        # seeds >= 4295 used to overflow numpy's 32-bit RandomState range
        dl = DataLoader(_arrays(16), 4, shuffle=True, seed=2 ** 31,
                        shard=False)
        assert len(list(dl)) == 4

    def test_length_mismatch_raises(self, hvd):
        with pytest.raises(ValueError, match="disagree"):
            DataLoader({"x": np.zeros((4, 1)), "y": np.zeros(5)}, 2)

    def test_oversized_batch_raises(self, hvd):
        with pytest.raises(ValueError, match="exceeds"):
            DataLoader(_arrays(4), 8, shard=False)
