"""Two-process TF-frontend worker: eager + tf.function collectives,
sparse IndexedSlices allreduce (allgather path), variable broadcast, and
DistributedGradientTape replica consistency."""

import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402

SCENARIO = sys.argv[1] if len(sys.argv) > 1 else "full"

hvd.init()
rank = hvd.process_rank()
nproc = hvd.num_processes()


def scenario_adasum():
    """Delta-model Adasum optimizer vs the pairwise oracle (mirrors the
    torch_worker adasum scenario; reference test_adasum_* structure):
    local SGD update, Adasum-combined parameter delta, verified against
    adasum_reduce_stack of the gathered per-rank deltas."""
    from horovod_tpu.ops import adasum as AD

    tf.random.set_seed(0)
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(8, activation="tanh", input_shape=(4,)),
        tf.keras.layers.Dense(1),
    ])
    hvd.broadcast_variables(model.variables, root_rank=0)
    lr = 0.05
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(lr), op=hvd.Adasum)
    # op=Adasum must select the DELTA optimizer, not gradient averaging.
    assert getattr(opt, "_hvd_adasum", False), type(opt).__mro__

    variables = model.trainable_variables
    start = [v.numpy().copy() for v in variables]
    x = tf.random.stateless_normal((16, 4), seed=[123 + rank, 1])
    y = tf.reduce_sum(x, axis=1, keepdims=True)
    with tf.GradientTape() as tape:
        loss = tf.reduce_mean((model(x) - y) ** 2)
    grads = tape.gradient(loss, variables)  # plain tape: LOCAL grads
    opt.apply_gradients(zip(grads, variables))

    # Oracle: each rank's local delta is -lr*g (plain SGD); gather and
    # reduce with the serial pairwise recursion.
    for i, (v, s, g) in enumerate(zip(variables, start, grads)):
        local_delta = (-lr * g.numpy()).reshape(1, -1)
        all_d = hvd.allgather(tf.constant(local_delta),
                              name=f"adasum.oracle.{i}").numpy()
        expect = s.reshape(-1) + np.asarray(AD.adasum_reduce_stack(all_d))
        np.testing.assert_allclose(
            v.numpy().reshape(-1), expect, rtol=1e-5, atol=1e-6)

    # Replicas must be identical after the sync step.
    flat = np.concatenate([v.numpy().ravel() for v in variables])
    gathered = hvd.allgather(tf.constant(flat[None, :])).numpy()
    for r in range(1, nproc):
        assert np.allclose(gathered[0], gathered[r], atol=1e-6), r

    # backward_passes_per_step=2: the first step applies only the LOCAL
    # update (replicas drift on different data); the second Adasum-
    # combines the cumulative drift and re-converges them.
    opt2 = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(lr), op=hvd.Adasum,
        backward_passes_per_step=2)
    for it in range(2):
        x = tf.random.stateless_normal((16, 4), seed=[500 + rank, it])
        y = tf.reduce_sum(x, axis=1, keepdims=True)
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean((model(x) - y) ** 2)
        grads = tape.gradient(loss, variables)
        opt2.apply_gradients(zip(grads, variables))
        flat = np.concatenate([v.numpy().ravel() for v in variables])
        gathered = hvd.allgather(
            tf.constant(flat[None, :]), name=f"adasum.k2.{it}").numpy()
        same = all(np.allclose(gathered[0], gathered[r], atol=1e-7)
                   for r in range(1, nproc))
        if it == 0:
            assert not same, "ranks must drift on the non-comm step"
        else:
            assert same, "comm step must re-converge the replicas"

    hvd.shutdown()
    print(f"TF-WORKER-OK rank={rank}")


if SCENARIO == "adasum":
    scenario_adasum()
    sys.exit(0)

assert nproc == 2

# dense eager allreduce
out = hvd.allreduce(tf.fill([4], float(rank + 1)), op=hvd.Sum)
assert np.allclose(out.numpy(), 3.0), out.numpy()

# sparse allreduce: each rank touches DIFFERENT embedding rows; the
# gathered IndexedSlices must contain both ranks' rows, averaged.
slices = tf.IndexedSlices(
    values=tf.fill([2, 3], float(rank + 1)),
    indices=tf.constant([rank * 2, rank * 2 + 1], tf.int64),
    dense_shape=tf.constant([8, 3], tf.int64),
)
red = hvd.allreduce(slices, op=hvd.Average, name="emb.grad")
assert isinstance(red, tf.IndexedSlices), type(red)
dense = tf.math.unsorted_segment_sum(red.values, red.indices, 8).numpy()
expect = np.zeros((8, 3), np.float32)
expect[0:2] = 1.0 / 2  # rank 0's rows, averaged over 2 participants
expect[2:4] = 2.0 / 2  # rank 1's rows
assert np.allclose(dense, expect), dense

# sparse_as_dense path gives the same dense result
red_d = hvd.allreduce(slices, op=hvd.Average, name="emb.grad.dense",
                      sparse_as_dense=True)
assert np.allclose(red_d.numpy(), expect), red_d.numpy()

# tf.function-embedded allreduce
@tf.function
def traced_sum(t):
    return hvd.allreduce(t, op=hvd.Sum, name="traced.t")

out = traced_sum(tf.constant([float(rank)]))
assert np.allclose(out.numpy(), [1.0]), out.numpy()

# broadcast_variables aligns divergent variables
v = tf.Variable([float(rank + 5)])
hvd.broadcast_variables([v], root_rank=0)
assert np.allclose(v.numpy(), [5.0]), v.numpy()

# DistributedGradientTape on different per-rank data keeps replicas equal
tf.random.set_seed(7)
model = tf.keras.Sequential([tf.keras.layers.Dense(1, input_shape=(4,))])
opt = tf.keras.optimizers.SGD(0.05)
hvd.broadcast_variables(model.variables, root_rank=0)
xr = tf.random.stateless_normal((16, 4), seed=[rank, 1])
yr = tf.reduce_sum(xr, axis=1, keepdims=True)
for _ in range(3):
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_mean((model(xr) - yr) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))
flat = np.concatenate([w.numpy().ravel() for w in model.trainable_variables])
gathered = hvd.allgather(tf.constant(flat[None, :]))
assert np.allclose(gathered[0], gathered[1], atol=1e-6), \
    np.abs(gathered.numpy()[0] - gathered.numpy()[1]).max()

hvd.shutdown()
print(f"TF-WORKER-OK rank={rank}")
