"""Two-process TF-frontend worker: eager + tf.function collectives,
sparse IndexedSlices allreduce (allgather path), variable broadcast, and
DistributedGradientTape replica consistency."""

import os
import sys

sys.path.insert(0, os.environ["REPO"])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402

hvd.init()
rank = hvd.process_rank()
nproc = hvd.num_processes()
assert nproc == 2

# dense eager allreduce
out = hvd.allreduce(tf.fill([4], float(rank + 1)), op=hvd.Sum)
assert np.allclose(out.numpy(), 3.0), out.numpy()

# sparse allreduce: each rank touches DIFFERENT embedding rows; the
# gathered IndexedSlices must contain both ranks' rows, averaged.
slices = tf.IndexedSlices(
    values=tf.fill([2, 3], float(rank + 1)),
    indices=tf.constant([rank * 2, rank * 2 + 1], tf.int64),
    dense_shape=tf.constant([8, 3], tf.int64),
)
red = hvd.allreduce(slices, op=hvd.Average, name="emb.grad")
assert isinstance(red, tf.IndexedSlices), type(red)
dense = tf.math.unsorted_segment_sum(red.values, red.indices, 8).numpy()
expect = np.zeros((8, 3), np.float32)
expect[0:2] = 1.0 / 2  # rank 0's rows, averaged over 2 participants
expect[2:4] = 2.0 / 2  # rank 1's rows
assert np.allclose(dense, expect), dense

# sparse_as_dense path gives the same dense result
red_d = hvd.allreduce(slices, op=hvd.Average, name="emb.grad.dense",
                      sparse_as_dense=True)
assert np.allclose(red_d.numpy(), expect), red_d.numpy()

# tf.function-embedded allreduce
@tf.function
def traced_sum(t):
    return hvd.allreduce(t, op=hvd.Sum, name="traced.t")

out = traced_sum(tf.constant([float(rank)]))
assert np.allclose(out.numpy(), [1.0]), out.numpy()

# broadcast_variables aligns divergent variables
v = tf.Variable([float(rank + 5)])
hvd.broadcast_variables([v], root_rank=0)
assert np.allclose(v.numpy(), [5.0]), v.numpy()

# DistributedGradientTape on different per-rank data keeps replicas equal
tf.random.set_seed(7)
model = tf.keras.Sequential([tf.keras.layers.Dense(1, input_shape=(4,))])
opt = tf.keras.optimizers.SGD(0.05)
hvd.broadcast_variables(model.variables, root_rank=0)
xr = tf.random.stateless_normal((16, 4), seed=[rank, 1])
yr = tf.reduce_sum(xr, axis=1, keepdims=True)
for _ in range(3):
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_mean((model(xr) - yr) ** 2)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))
flat = np.concatenate([w.numpy().ravel() for w in model.trainable_variables])
gathered = hvd.allgather(tf.constant(flat[None, :]))
assert np.allclose(gathered[0], gathered[1], atol=1e-6), \
    np.abs(gathered.numpy()[0] - gathered.numpy()[1]).max()

hvd.shutdown()
print(f"TF-WORKER-OK rank={rank}")
