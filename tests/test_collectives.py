"""Collective-op tests, in-graph (shard_map over the 8-device mesh) and
eager.  Mirrors the reference's framework op tests
(test/test_tensorflow.py allreduce cpu/fused/average, allgather,
broadcast; test/test_torch.py async/handle tests)."""

import jax
import jax.numpy as jnp
import time
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import spmd

N = 8


def _per_worker(shape, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(N, *shape).astype(dtype)


def run_per_worker(fn, *arrays, out_spec=P(hvd.AXIS)):
    """Run fn under shard_map; each worker sees arrays[i] (dim 0 stripped
    by giving each worker a leading slice of size 1)."""

    def inner(*xs):
        return fn(*[x[0] for x in xs])

    wrapped = spmd.shard(
        inner,
        in_specs=tuple(P(hvd.AXIS) for _ in arrays),
        out_specs=out_spec,
    )
    return jax.jit(wrapped)(*arrays)


class TestInGraphAllreduce:
    def test_sum(self):
        x = _per_worker((4, 5))
        out = run_per_worker(lambda t: hvd.allreduce(t, hvd.Sum)[None], x)
        expect = x.sum(axis=0)
        for i in range(N):
            np.testing.assert_allclose(np.asarray(out[i]), expect, rtol=1e-5)

    def test_average(self):
        x = _per_worker((3, 7))
        out = run_per_worker(lambda t: hvd.allreduce(t, hvd.Average)[None], x)
        expect = x.mean(axis=0)
        np.testing.assert_allclose(np.asarray(out[0]), expect, rtol=1e-5)

    def test_min_max(self):
        x = _per_worker((6,))
        mn = run_per_worker(lambda t: hvd.allreduce(t, hvd.Min)[None], x)
        mx = run_per_worker(lambda t: hvd.allreduce(t, hvd.Max)[None], x)
        np.testing.assert_allclose(np.asarray(mn[0]), x.min(axis=0), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(mx[0]), x.max(axis=0), rtol=1e-6)

    def test_product(self):
        x = np.abs(_per_worker((4,))) + 0.5
        out = run_per_worker(lambda t: hvd.allreduce(t, hvd.Product)[None], x)
        np.testing.assert_allclose(np.asarray(out[0]), x.prod(axis=0), rtol=1e-4)

    def test_prescale_postscale(self):
        x = _per_worker((4,))
        out = run_per_worker(
            lambda t: hvd.allreduce(
                t, hvd.Sum, prescale_factor=2.0, postscale_factor=0.5
            )[None],
            x,
        )
        np.testing.assert_allclose(np.asarray(out[0]), x.sum(axis=0), rtol=1e-5)

    def test_pytree(self):
        a = _per_worker((2,))
        b = _per_worker((3,), seed=1)
        out = run_per_worker(
            lambda u, v: jax.tree_util.tree_map(
                lambda t: t[None], hvd.allreduce({"a": u, "b": v}, hvd.Sum)
            ),
            a,
            b,
            out_spec={"a": P(hvd.AXIS), "b": P(hvd.AXIS)},
        )
        np.testing.assert_allclose(np.asarray(out["a"][0]), a.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["b"][0]), b.sum(0), rtol=1e-5)

    def test_hierarchical_axes(self):
        """Two-axis allreduce over the (cross, local) mesh — the
        hierarchical path (NCCLHierarchicalAllreduce analogue)."""
        hm = hvd.hierarchical_mesh()
        x = _per_worker((4,)).reshape(hm.devices.shape + (4,))

        def inner(t):
            return hvd.allreduce(
                t[0, 0], hvd.Sum, axis_name=("cross", "local")
            )[None, None]

        out = jax.jit(
            spmd.shard(
                inner,
                in_specs=(P("cross", "local"),),
                out_specs=P("cross", "local"),
                mesh=hm,
            )
        )(x)
        np.testing.assert_allclose(
            np.asarray(out)[0, 0], x.sum(axis=(0, 1)), rtol=1e-5
        )

    def test_unbound_axis_raises(self):
        with pytest.raises(RuntimeError, match="worker axis"):
            jax.jit(lambda t: hvd.allreduce(t, hvd.Sum))(jnp.ones((3,)))


class TestInGraphOthers:
    def test_allgather(self):
        x = _per_worker((2, 3))
        out = run_per_worker(
            lambda t: hvd.allgather(t)[None], x, out_spec=P(hvd.AXIS)
        )
        expect = x.reshape(N * 2, 3)
        np.testing.assert_allclose(np.asarray(out[0]), expect, rtol=1e-6)

    def test_broadcast(self):
        x = _per_worker((4,))
        for root in (0, 3, 7):
            out = run_per_worker(
                lambda t: hvd.broadcast(t, root_rank=root)[None], x
            )
            for i in range(N):
                np.testing.assert_allclose(
                    np.asarray(out[i]), x[root], rtol=1e-6
                )

    def test_alltoall(self):
        x = _per_worker((N, 2))
        out = run_per_worker(lambda t: hvd.alltoall(t)[None], x)
        # worker i receives row i from every worker
        for i in range(N):
            expect = x[:, i, :]
            np.testing.assert_allclose(np.asarray(out[i]), expect, rtol=1e-6)

    def test_reducescatter(self):
        x = _per_worker((N * 2, 3))
        out = run_per_worker(lambda t: hvd.reducescatter(t, hvd.Sum)[None], x)
        full = x.sum(axis=0)
        for i in range(N):
            np.testing.assert_allclose(
                np.asarray(out[i]), full[i * 2 : (i + 1) * 2], rtol=1e-5
            )


class TestEager:
    """Single-process eager semantics.

    Worker count is CHIPS (`hvd.size()` — here the 8 virtual devices of
    the test mesh), and an eager submission stands for every local chip,
    so Sum is chip-weighted (local_size ×) while Average/Min/Max are
    identities — exactly the in-graph worker-axis semantics."""

    def test_allreduce_identity(self):
        x = np.random.randn(5, 4).astype(np.float32)
        ls = hvd.local_size()
        np.testing.assert_allclose(hvd.allreduce(x, hvd.Sum), ls * x,
                                   rtol=1e-6)
        np.testing.assert_allclose(hvd.allreduce(x, hvd.Average), x)

    def test_weighted_product_min_max(self):
        """Chip-weighted contract for the remaining reduce ops: Product
        raises to the local chip count; Min/Max are duplicate-
        insensitive identities at one process."""
        ls = hvd.local_size()
        x = np.asarray([0.5, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(
            hvd.allreduce(x, hvd.Product), x ** ls, rtol=1e-6)
        np.testing.assert_allclose(hvd.allreduce(x, hvd.Min), x)
        np.testing.assert_allclose(hvd.allreduce(x, hvd.Max), x)

    def test_process_sum_identity(self):
        """process_sum: exactly one contribution per process regardless
        of chip count."""
        x = np.random.randn(4).astype(np.float32)
        np.testing.assert_allclose(hvd.process_sum(x), x, rtol=1e-6)

    def test_allgather_identity(self):
        x = np.random.randn(3, 2).astype(np.float32)
        np.testing.assert_allclose(hvd.allgather(x), x)

    def test_broadcast_identity(self):
        x = np.random.randn(3).astype(np.float32)
        np.testing.assert_allclose(hvd.broadcast(x, 0), x)

    def test_grouped_allreduce(self):
        xs = [np.random.randn(4).astype(np.float32) for _ in range(5)]
        outs = hvd.grouped_allreduce(xs, hvd.Sum)
        ls = hvd.local_size()
        for a, b in zip(outs, xs):
            np.testing.assert_allclose(a, ls * b, rtol=1e-6)

    def test_grouped_allreduce_scaling_kwargs(self):
        """prescale/postscale must reach every eager grouped path (the
        native route forwards them per tensor; the direct routes scale
        around the reduction) — silently dropping them was the r4
        advisor finding."""
        xs = [np.random.randn(4).astype(np.float32) for _ in range(3)]
        ls = hvd.local_size()
        outs = hvd.grouped_allreduce(
            xs, hvd.Sum, prescale_factor=0.5, postscale_factor=4.0)
        for a, b in zip(outs, xs):
            np.testing.assert_allclose(a, 2.0 * ls * b, rtol=1e-5)

    def test_grouped_adasum_scaling_kwargs(self):
        """Single-process Adasum is the identity, so the scales are
        directly observable: out = post * adasum(pre * x)."""
        xs = [np.random.randn(4).astype(np.float32) for _ in range(2)]
        outs = hvd.grouped_allreduce(
            xs, hvd.Adasum, prescale_factor=0.5, postscale_factor=4.0)
        for a, b in zip(outs, xs):
            np.testing.assert_allclose(a, 2.0 * b, rtol=1e-5)

    def test_grouped_allreduce_rejects_unknown_kwargs(self):
        with pytest.raises(TypeError, match="unsupported kwargs"):
            hvd.grouped_allreduce([np.ones(3, np.float32)], hvd.Adasum,
                                  bogus_knob=1)

    def test_barrier(self):
        hvd.barrier()

    def test_bad_op(self):
        with pytest.raises(ValueError, match="Unknown reduce op"):
            hvd.allreduce(np.ones(3), "Mean")


class TestAsyncHandles:
    """Handle-based API (torch/mpi_ops.py synchronize/poll parity)."""

    def test_allreduce_async_synchronize(self):
        x = np.random.randn(4).astype(np.float32)
        h = hvd.allreduce_async(x, hvd.Sum)
        # Genuinely asynchronous under the native runtime: poll flips true
        # once the negotiation cycle completes the op.
        deadline = time.time() + 10
        while not hvd.poll(h):
            assert time.time() < deadline
            time.sleep(0.001)
        out = hvd.synchronize(h)
        np.testing.assert_allclose(out, hvd.local_size() * x, rtol=1e-6)

    def test_handle_single_use(self):
        h = hvd.allreduce_async(np.ones(2, np.float32))
        hvd.synchronize(h)
        with pytest.raises(ValueError, match="handle"):
            hvd.synchronize(h)

    def test_multiple_outstanding(self):
        xs = [np.random.randn(3).astype(np.float32) for _ in range(4)]
        handles = [hvd.allreduce_async(x, hvd.Sum, name=f"t{i}") for i, x in enumerate(xs)]
        for h, x in zip(handles, xs):
            np.testing.assert_allclose(
                hvd.synchronize(h), hvd.local_size() * x, rtol=1e-6)

    def test_broadcast_allgather_alltoall_async(self):
        x = np.random.randn(8, 2).astype(np.float32)
        np.testing.assert_allclose(
            hvd.synchronize(hvd.broadcast_async(x, 0)), x
        )
        np.testing.assert_allclose(
            hvd.synchronize(hvd.allgather_async(x)), x
        )
        np.testing.assert_allclose(
            hvd.synchronize(hvd.alltoall_async(x)), x
        )


class TestCompression:
    def test_fp16_roundtrip(self):
        x = np.random.randn(16).astype(np.float32)
        comp, ctx = hvd.Compression.fp16.compress(x)
        assert jnp.asarray(comp).dtype == jnp.float16
        out = hvd.Compression.fp16.decompress(comp, ctx)
        assert jnp.asarray(out).dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), x, atol=1e-2)

    def test_bf16_in_allreduce(self):
        x = _per_worker((4,))
        out = run_per_worker(
            lambda t: hvd.allreduce(t, hvd.Sum, compression=hvd.Compression.bf16)[
                None
            ],
            x,
        )
        np.testing.assert_allclose(np.asarray(out[0]), x.sum(0), rtol=0.05, atol=0.05)
        assert np.asarray(out).dtype == np.float32

    def test_none(self):
        x = np.ones(3, np.float32)
        c, ctx = hvd.Compression.none.compress(x)
        assert c is x
        assert hvd.Compression.none.decompress(c, ctx) is x
