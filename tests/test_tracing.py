"""Distributed request tracing (obs/tracing.py span layer +
obs/trace_store.py collector + obs/trace CLI).

Four layers of proof:

* **Span model / recorder**: JSONL stream shapes (anchor, start,
  event, finish, detail, drop), durable start-before-kill ordering,
  the closed typed-event vocabulary, deterministic cross-process head
  sampling.
* **Tail sampling**: full tick-level detail retained ONLY for traces
  that error, carry a typed event (failover/resume/eviction/...), are
  forced via ``X-Trace-Sampled``, exceed the latency threshold, or
  head-sample in; everything else keeps just the breakdown on the
  finish record (+ a drop marker).
* **Collector**: trees assembled ACROSS streams with wall-clock
  anchor alignment, unfinished spans (a SIGKILL'd process's evidence)
  surfaced, autopsy JSON / ASCII tree / Perfetto export.
* **Ingress validation**: ``X-Parent-Span`` honored only alongside a
  valid propagated ``X-Trace-Id``; malformed / oversized / spoofed
  parents dropped at the replica's HTTP ingress.  (Router-ingress
  twins live in tests/test_router.py.)
"""

import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T
from horovod_tpu.obs import tracing as TR
from horovod_tpu.obs.trace import main as trace_cli
from horovod_tpu.obs.trace_store import TraceStore
from horovod_tpu.serving.journal import RequestJournal

from conftest import http_post_json as _post  # noqa: E402

pytestmark = pytest.mark.tracing


def _cfg():
    return T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _engine(model, **kw):
    params, cfg = model
    defaults = dict(n_slots=2, max_len=40, min_prefill_bucket=4,
                    restart_backoff=0.01, restart_backoff_max=0.05)
    defaults.update(kw)
    return serving.InferenceEngine(
        params, cfg, serving.EngineConfig(**defaults))


def _run_until_done(engine, futs, max_ticks=400):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


@pytest.fixture()
def spans(tmp_path):
    """A started span recorder (high latency threshold: nothing
    retains by accident), detached afterwards so the module global
    never leaks into other tests."""
    assert TR.spans() is None
    rec = TR.start_spans(
        str(tmp_path / "proc.spans.jsonl"), proc="testproc",
        role="replica",
        sampling=TR.SpanSampling(latency_threshold_s=600.0))
    yield rec, tmp_path
    if TR.spans() is None:
        TR.activate_spans(rec)
    TR.stop_spans()


def _lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# span model + recorder
# ---------------------------------------------------------------------------


class TestSpanModel:
    def test_mint_and_validate_ids(self):
        sid = TR.mint_span_id()
        assert TR.valid_span_id(sid) and len(sid) == 16
        assert TR.valid_span_id("edge-abc.01_2")
        assert not TR.valid_span_id("")
        assert not TR.valid_span_id(None)
        assert not TR.valid_span_id("x" * 65)        # oversized
        assert not TR.valid_span_id("bad span!")     # bad charset

    def test_head_sampling_is_deterministic_and_rate_shaped(self):
        ids = [TR.mint_trace_id() for _ in range(400)]
        a = [TR.head_sampled(t, 0.25) for t in ids]
        b = [TR.head_sampled(t, 0.25) for t in ids]
        assert a == b                      # same verdict, any process
        frac = sum(a) / len(a)
        assert 0.1 < frac < 0.45           # roughly the asked-for rate
        assert not any(TR.head_sampled(t, 0.0) for t in ids)
        assert all(TR.head_sampled(t, 1.0) for t in ids)

    def test_stream_shapes_and_anchor(self, spans):
        rec, tmp = spans
        tid = TR.mint_trace_id()
        sid = rec.begin("root", tid, attrs={"x": 1})
        rec.event(tid, sid, "failover", {"replica": "r0g0"})
        rec.finish(sid, status="ok")
        lines = _lines(rec.path)
        assert lines[0]["k"] == "anchor"
        assert lines[0]["proc"] == "testproc"
        assert lines[0]["role"] == "replica"
        # anchor pairs the two clocks for collector-side alignment
        assert abs((lines[0]["wall"] - lines[0]["mono"])
                   - (time.time() - time.monotonic())) < 5.0
        s, e, f = lines[1], lines[2], lines[3]
        assert (s["k"], s["id"], s["trace"], s["name"]) \
            == ("s", sid, tid, "root")
        assert (e["k"], e["type"], e["span"]) == ("e", "failover", sid)
        assert (f["k"], f["id"], f["status"]) == ("f", sid, "ok")

    def test_event_vocabulary_is_closed(self, spans):
        rec, _ = spans
        with pytest.raises(ValueError, match="unknown span event"):
            rec.event(TR.mint_trace_id(), None, "exploded")

    def test_start_spans_is_single_per_process(self, spans):
        with pytest.raises(ValueError, match="already started"):
            TR.start_spans("/tmp/nope.jsonl")

    def test_request_begin_is_flushed_before_resolution(self, spans):
        """The durability contract: the start record is ON DISK the
        moment the request is live — a SIGKILL any time later still
        leaves the span for the autopsy."""
        rec, _ = spans
        tr = TR.RequestTrace("durable-1")
        tr.submitted_at = time.monotonic()
        rec.request_begin(tr)
        kinds = [l["k"] for l in _lines(rec.path)]
        assert kinds[-1] == "s"  # flushed, without any finish yet


# ---------------------------------------------------------------------------
# tail sampling
# ---------------------------------------------------------------------------


class TestTailSampling:
    def _resolved_trace(self, trace_id=None, *, dur=0.001, ticks=3,
                        error=None, events=(), sampled=False):
        tr = TR.RequestTrace(trace_id)
        now = time.monotonic()
        tr.submitted_at = now - dur
        tr.admitted_at = tr.submitted_at + dur / 4
        tr.first_token_at = tr.submitted_at + dur / 2
        tr.finished_at = now
        tr.finish, tr.error = ("length", None) if error is None \
            else (None, error)
        tr.sampled = sampled
        tr.ticks = [(now - 1e-3 * (i + 1), now - 1e-3 * i, 1)
                    for i in range(ticks)]
        for ev in events:
            tr.events.append((ev, now, None))
        return tr

    def _names(self, rec):
        return [l.get("name") for l in _lines(rec.path)
                if l["k"] == "d"]

    def test_clean_fast_request_tail_drops_detail(self, spans):
        rec, _ = spans
        tr = self._resolved_trace()
        rec.request_begin(tr)
        rec.request_done(tr)
        lines = _lines(rec.path)
        assert not [l for l in lines if l["k"] == "d"]  # no detail
        drop = [l for l in lines if l["k"] == "x"]
        assert drop and drop[0]["n"] == 3 and drop[0]["why"] == "tail"
        fin = [l for l in lines if l["k"] == "f"][0]
        # the breakdown is KEPT on the finish record
        assert fin["a"]["total_s"] is not None
        assert "retained" not in fin["a"]
        assert rec.n_dropped == 1 and rec.n_retained == 0

    def test_routine_spec_fallback_does_not_force_retention(self,
                                                            spans):
        """spec_fallback is a ROUTINE event under low-acceptance
        speculative load — it stays visible as an event record but
        must not drag full tick detail past tail sampling (only the
        failure-class RETAIN_EVENT_TYPES do)."""
        rec, _ = spans
        tr = self._resolved_trace()
        rec.request_begin(tr)
        rec.request_event(tr, "spec_fallback", {"slot": 0})
        rec.request_done(tr)
        lines = _lines(rec.path)
        assert [l for l in lines if l["k"] == "e"
                and l["type"] == "spec_fallback"]  # event IS recorded
        fin = [l for l in lines if l["k"] == "f"][-1]
        assert "retained" not in fin["a"]          # ... detail is not
        assert not [l for l in lines if l["k"] == "d"]
        assert "spec_fallback" not in TR.RETAIN_EVENT_TYPES
        assert TR.RETAIN_EVENT_TYPES < TR.SPAN_EVENT_TYPES

    @pytest.mark.parametrize("kw,reason", [
        (dict(error="EngineFailedError"), "error"),
        (dict(sampled=True), "forced"),
        (dict(events=("resume",)), "event"),
        (dict(events=("eviction",)), "event"),
        (dict(dur=1000.0), "latency"),
    ])
    def test_retention_reasons(self, spans, kw, reason):
        rec, _ = spans
        tr = self._resolved_trace(**kw)
        rec.request_begin(tr)
        rec.request_done(tr)
        fin = [l for l in _lines(rec.path) if l["k"] == "f"][-1]
        assert fin["a"]["retained"] == reason
        names = self._names(rec)
        assert names.count("tick") == 3
        assert {"queue", "prefill", "decode"} <= set(names)

    def test_head_sampling_retains(self, tmp_path):
        rec = TR.SpanRecorder(str(tmp_path / "h.jsonl"), proc="h",
                              sampling=TR.SpanSampling(
                                  latency_threshold_s=600.0,
                                  head_rate=1.0))
        tr = self._resolved_trace()
        rec.request_begin(tr)
        rec.request_done(tr)
        rec.close()
        fin = [l for l in _lines(rec.path) if l["k"] == "f"][0]
        assert fin["a"]["retained"] == "head"

    def test_tick_span_cap(self, tmp_path):
        rec = TR.SpanRecorder(str(tmp_path / "c.jsonl"), proc="c",
                              sampling=TR.SpanSampling(
                                  latency_threshold_s=600.0,
                                  max_tick_spans=4))
        tr = self._resolved_trace(ticks=9, error="Boom")
        tr.ticks_overflow = 7   # ticks past the RequestTrace buffer cap
        rec.request_begin(tr)
        rec.request_done(tr)
        rec.close()
        lines = _lines(rec.path)
        assert sum(1 for l in lines
                   if l["k"] == "d" and l["name"] == "tick") == 4
        cap = [l for l in lines if l["k"] == "x"][0]
        # shed = (9 buffered - 4 written) + 7 never buffered
        assert cap["n"] == 12 and cap["why"] == "max_tick_spans"


# ---------------------------------------------------------------------------
# collector: trees, clock alignment, autopsy, renders
# ---------------------------------------------------------------------------


class TestTraceStore:
    def _two_process_trace(self, tmp_path, *, finish_child=True):
        """A router-shaped trace across two streams with DIFFERENT
        clock anchors: router at wall offset 0, replica with its
        monotonic clock shifted by 100s (collector must re-align)."""
        tid = "autopsy-1"
        router = TR.SpanRecorder(str(tmp_path / "router.spans.jsonl"),
                                 proc="router", role="router")
        root = router.begin("router /generate", tid, t0=time.monotonic())
        att1 = router.begin("attempt 1 -> r0g0", tid, parent=root)
        rep = TR.SpanRecorder(str(tmp_path / "r0g0.spans.jsonl"),
                              proc="r0g0", role="replica")
        # fake a skewed monotonic clock: shift mono anchor by -100
        lines = _lines(rep.path)
        rep.close()
        lines[0]["mono"] -= 100.0
        with open(rep.path, "w") as f:
            f.write(json.dumps(lines[0]) + "\n")
        rep = TR.SpanRecorder(str(tmp_path / "r0g0b.spans.jsonl"),
                              proc="r0g0", role="replica")
        child = rep.begin("generate", tid, parent=att1,
                          t0=time.monotonic(),
                          attrs={"prompt_tokens": 3})
        router.event(tid, root, "failover", {"replica": "r0g0"})
        router.event(tid, root, "resume",
                     {"carried": 5, "from_replica": "r0g0"})
        att2 = router.begin("attempt 2 -> r1g0", tid, parent=root)
        if finish_child:
            rep.finish(child, status="ok", attrs={"tokens": 4})
        router.finish(att1, status="error:connection")
        router.finish(att2, status="http:200")
        router.finish(root, status="http:200",
                      attrs={"attempts": 2, "resumed": True})
        router.close()
        rep.close()
        return tid

    def test_tree_assembly_and_clock_alignment(self, tmp_path):
        tid = self._two_process_trace(tmp_path)
        store = TraceStore.from_dir(str(tmp_path))
        roots = store.tree(tid)
        assert len(roots) == 1
        root = roots[0]
        assert root.name == "router /generate"
        att_names = [c.name for c in root.children]
        assert att_names == ["attempt 1 -> r0g0", "attempt 2 -> r1g0"]
        child = root.children[0].children[0]
        assert child.proc == "r0g0" and child.name == "generate"
        # clock alignment: the replica's span must land on the SAME
        # wall axis as the router's (within the test's runtime), not
        # 100 seconds away
        assert abs(child.t0 - root.t0) < 5.0

    def test_autopsy_fields(self, tmp_path):
        tid = self._two_process_trace(tmp_path)
        a = TraceStore.from_dir(str(tmp_path)).autopsy(tid)
        assert a["trace_id"] == tid
        assert set(a["processes"]) == {"router", "r0g0"}
        assert a["resumed"] is True
        assert a["failovers"] == 1
        assert a["carried_tokens"] == 5
        assert a["span_count"] == 4
        assert not a["unfinished_spans"]
        assert len(a["attempts"]) == 3  # 2 router attempts + 1 replica
        assert a["duration_s"] is not None

    def test_unfinished_span_surfaces_kill_evidence(self, tmp_path):
        tid = self._two_process_trace(tmp_path, finish_child=False)
        store = TraceStore.from_dir(str(tmp_path))
        a = store.autopsy(tid)
        assert len(a["unfinished_spans"]) == 1
        txt = store.ascii_tree(tid)
        assert "UNFINISHED" in txt
        rep_attempt = [x for x in a["attempts"] if x["proc"] == "r0g0"]
        assert rep_attempt[0]["unfinished"] is True
        assert rep_attempt[0]["status"] == "unfinished"

    def test_ascii_tree_and_perfetto(self, tmp_path):
        tid = self._two_process_trace(tmp_path)
        store = TraceStore.from_dir(str(tmp_path))
        txt = store.ascii_tree(tid)
        assert "router /generate [router]" in txt
        assert "generate [r0g0]" in txt
        assert "! failover" in txt and "! resume" in txt
        events = store.perfetto(tid)
        procs = {e["args"]["name"] for e in events
                 if e.get("name") == "process_name"}
        assert procs == {"router", "r0g0"}       # one track per process
        spans = [e for e in events if e.get("ph") == "X"]
        assert len(spans) == 4
        instants = {e["name"] for e in events if e.get("ph") == "i"}
        assert {"failover", "resume"} <= instants

    def test_perfetto_concurrent_requests_get_distinct_rows(
            self, tmp_path):
        """Two OVERLAPPING request spans in one process must land on
        different thread rows (same-row overlap renders as a bogus
        flame nesting in Perfetto), while a request's own children
        (phases/ticks) share its row — true nesting."""
        rec = TR.SpanRecorder(str(tmp_path / "p.jsonl"), proc="rep",
                              role="replica")
        t0 = time.monotonic()
        a = rec.begin("generate", "ta", t0=t0)
        b = rec.begin("generate", "tb", t0=t0 + 0.001)  # overlaps a
        rec.finish(a, t1=t0 + 0.1)
        rec.finish(b, t1=t0 + 0.1)
        rec.close()
        store = TraceStore([str(tmp_path / "p.jsonl")])
        pf = store.perfetto()  # combined export: all traces, one file
        ev_a = [e for e in pf if e.get("ph") == "X"
                and e["args"]["trace_id"] == "ta"][0]
        ev_b = [e for e in pf if e.get("ph") == "X"
                and e["args"]["trace_id"] == "tb"][0]
        assert ev_a["pid"] == ev_b["pid"]      # same process track
        assert ev_a["tid"] != ev_b["tid"]      # distinct rows
        # a retained trace's detail spans inherit the request's row
        tid2 = self._two_process_trace(tmp_path)
        pf = TraceStore.from_dir(str(tmp_path)).perfetto(tid2)
        by_span = {e["args"]["span_id"]: e for e in pf
                   if e.get("ph") == "X" and "span_id" in e.get(
                       "args", {})}
        root = [e for e in pf if e.get("ph") == "X"
                and e["name"] == "router /generate"][0]
        atts = [e for e in pf if e.get("ph") == "X"
                and e["name"].startswith("attempt")]
        assert all(a["tid"] == root["tid"] and a["pid"] == root["pid"]
                   for a in atts)  # one request = one router row

    def test_unknown_trace_and_unreadable_stream(self, tmp_path):
        tid = self._two_process_trace(tmp_path)
        (tmp_path / "garbage.spans.jsonl").write_text("{not json\n")
        (tmp_path / "empty.spans.jsonl").write_text("")
        # a stray BINARY file matching the glob must be skipped, not
        # abort the whole load with UnicodeDecodeError
        (tmp_path / "binary.spans.jsonl").write_bytes(
            b"\x80\x81\xfe\xff\x00binary")
        # ... as must individually malformed records: valid JSON of
        # the wrong shape (null timestamps, a bare list, a foreign
        # schema) skips the RECORD, never kills the store
        (tmp_path / "foreign.spans.jsonl").write_text(
            '{"k":"s","id":"m1","trace":"autopsy-1","t0":null}\n'
            '[1,2,3]\n'
            '{"k":"f","id":"m1","t1":"soon"}\n'
            '{"some":"other","jsonl":"schema"}\n')
        store = TraceStore.from_dir(str(tmp_path))
        assert store.autopsy("nonexistent") is None
        assert store.autopsy(tid) is not None  # healthy streams intact
        store2 = TraceStore([str(tmp_path / "missing-*.jsonl"),
                             str(tmp_path / "does_not_exist.jsonl")])
        assert store2.trace_ids() == []

    def test_torn_final_line_tolerated(self, tmp_path):
        tid = self._two_process_trace(tmp_path)
        with open(tmp_path / "r0g0b.spans.jsonl", "a") as f:
            f.write('{"k":"s","id":"torn","trace":"autopsy-1","t0"')
        a = TraceStore.from_dir(str(tmp_path)).autopsy(tid)
        assert a["span_count"] == 4  # torn line skipped, rest intact

    def test_cli_list_tree_json_perfetto(self, tmp_path, capsys):
        tid = self._two_process_trace(tmp_path)
        assert trace_cli(["--spans", str(tmp_path), "--list"]) == 0
        out = capsys.readouterr().out
        assert tid in out and "resumed" in out
        assert trace_cli(["--spans", str(tmp_path), tid]) == 0
        assert "router /generate" in capsys.readouterr().out
        assert trace_cli(["--spans", str(tmp_path), tid, "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["carried_tokens"] == 5
        pf = str(tmp_path / "out.perfetto.json")
        assert trace_cli(["--spans", str(tmp_path), tid,
                          "--perfetto", pf]) == 0
        capsys.readouterr()
        assert json.load(open(pf))
        assert trace_cli(["--spans", str(tmp_path), "bogus-id"]) == 1


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


class TestEngineSpans:
    def test_request_span_with_parent_and_forced_detail(self, model,
                                                        spans):
        rec, _ = spans
        eng = _engine(model)
        eng.warmup([4])
        fut = eng.submit([1, 2, 3], max_new_tokens=5,
                         trace_id="edge-req", parent_span="p" * 16,
                         sampled=True)
        _run_until_done(eng, [fut])
        eng.stop()
        lines = _lines(rec.path)
        start = [l for l in lines if l["k"] == "s"
                 and l["trace"] == "edge-req"][0]
        assert start["parent"] == "p" * 16
        assert start["a"]["prompt_tokens"] == 3
        fin = [l for l in lines if l["k"] == "f"
               and l["id"] == start["id"]][0]
        assert fin["status"] == "ok"
        assert fin["a"]["retained"] == "forced"
        assert fin["a"]["tokens"] == 5
        ticks = [l for l in lines if l["k"] == "d"
                 and l["trace"] == "edge-req" and l["name"] == "tick"]
        # 5 tokens = 1 prefill + 4 decode-tick emissions
        assert len(ticks) == 4
        assert all(l["parent"] == start["id"] for l in ticks)

    def test_clean_request_detail_dropped_breakdown_kept(self, model,
                                                         spans):
        rec, _ = spans
        eng = _engine(model)
        eng.warmup([4])
        fut = eng.submit([1, 2, 3], max_new_tokens=5)
        _run_until_done(eng, [fut])
        eng.stop()
        tid = fut.trace_id
        lines = _lines(rec.path)
        assert not [l for l in lines if l["k"] == "d"
                    and l["trace"] == tid]
        fin = [l for l in lines if l["k"] == "f"][-1]
        assert fin["a"]["decode_ticks"] == 4     # breakdown survives
        assert [l for l in lines if l["k"] == "x"
                and l["trace"] == tid]

    def test_restart_resume_emits_typed_event_same_span(self, model,
                                                        spans):
        """A crash mid-decode, restart-resume ON: the resumed request
        keeps its span id, the stream carries the typed ``resume``
        event on that same span, and retention flips to full detail."""
        rec, _ = spans
        inj = serving.FaultInjector()
        eng = _engine(model, faults=inj, max_restarts=3)
        eng.warmup([4])
        inj.add(serving.FaultSpec(
            site="decode_tick", kind="raise",
            skip=inj.visits("decode_tick") + 2, max_fires=1))
        fut = eng.submit([1, 2, 3], max_new_tokens=8)
        _run_until_done(eng, [fut])
        eng.stop()
        assert fut.finish_reason == "length"
        lines = _lines(rec.path)
        start = [l for l in lines if l["k"] == "s"
                 and l["trace"] == fut.trace_id][0]
        evs = [l for l in lines if l["k"] == "e"
               and l["trace"] == fut.trace_id]
        assert [e["type"] for e in evs] == ["engine_restart", "resume"]
        assert all(e["span"] == start["id"] for e in evs)  # ONE tree
        assert evs[1]["a"]["wasted_tokens"] >= 1
        fin = [l for l in lines if l["k"] == "f"
               and l["id"] == start["id"]][0]
        assert fin["a"]["retained"] == "event"
        # the response breakdown discloses the events too
        assert [e["type"] for e in fut.breakdown()["events"]] \
            == ["engine_restart", "resume"]

    def test_journal_carries_span_id(self, model, spans, tmp_path):
        """Satellite regression (beside the resume-failover tests):
        the journal's begin record carries the originating span id, so
        a post-mortem ``read_live`` descriptor links the resumed
        attempt into the SAME trace tree."""
        rec, _ = spans
        jp = str(tmp_path / "req.journal.jsonl")
        eng = _engine(model, journal_path=jp)
        eng.warmup([4])
        fut = eng.submit([1, 2, 3], max_new_tokens=20,
                         trace_id="kill-me")
        for _ in range(6):
            eng.step()
        assert not fut.done()
        live = RequestJournal.read_live(jp)
        desc = live["kill-me"]
        assert desc["span_id"] == fut.trace.span_id
        assert len(desc["emitted_tokens"]) >= 1
        fut.cancel()
        _run_until_done(eng, [fut])
        eng.stop()

    def test_disabled_recorder_leaves_no_trace_state(self, model):
        assert TR.spans() is None
        eng = _engine(model)
        eng.warmup([4])
        fut = eng.submit([1, 2, 3], max_new_tokens=5)
        _run_until_done(eng, [fut])
        eng.stop()
        assert fut.trace.ticks == []      # no buffering when disabled
        assert fut.breakdown().get("events") is None


# ---------------------------------------------------------------------------
# replica HTTP ingress: header validation edge cases
# ---------------------------------------------------------------------------


class TestReplicaIngressHeaders:
    @pytest.fixture()
    def server(self, model, spans):
        rec, _ = spans
        eng = _engine(model)
        eng.warmup([4])
        srv = serving.ServingServer(eng, port=0).start()
        host, port = srv.address
        yield rec, f"http://{host}:{port}/generate"
        srv.stop(drain_timeout=5.0)

    def _post_hdrs(self, url, payload, headers):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **headers},
            method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())

    def _start_for(self, rec, tid):
        return [l for l in _lines(rec.path)
                if l["k"] == "s" and l["trace"] == tid]

    def _fin_for(self, rec, span_id, timeout=5.0):
        """The span's finish record, POLLED: the HTTP response is sent
        when the future resolves (`_done.set()`), which happens just
        BEFORE request_done appends the finish line — a fixed-point
        read right after the response races the recorder."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            fins = [l for l in _lines(rec.path)
                    if l["k"] == "f" and l["id"] == span_id]
            if fins:
                return fins[0]
            time.sleep(0.02)
        raise AssertionError(f"no finish record for span {span_id}")

    def test_valid_parent_with_valid_trace_propagates(self, server):
        rec, url = server
        code, body = self._post_hdrs(
            url, {"tokens": [1, 2], "max_new_tokens": 2},
            {"X-Trace-Id": "prop-1", "X-Parent-Span": "a" * 16,
             "X-Trace-Sampled": "1"})
        assert code == 200
        start = self._start_for(rec, "prop-1")[0]
        assert start["parent"] == "a" * 16
        fin = self._fin_for(rec, start["id"])
        assert fin["a"]["retained"] == "forced"  # X-Trace-Sampled

    def test_spoofed_parent_on_fresh_trace_is_dropped(self, server):
        """X-Parent-Span WITHOUT a propagated trace id: the parent
        would dangle into some other tenant's tree — dropped, the
        request roots its own trace."""
        rec, url = server
        code, body = self._post_hdrs(
            url, {"tokens": [1, 2], "max_new_tokens": 2},
            {"X-Parent-Span": "b" * 16})
        assert code == 200
        start = self._start_for(rec, body["trace_id"])[0]
        assert "parent" not in start

    @pytest.mark.parametrize("bad", [
        "has spaces", "x" * 65, "<script>", ""])
    def test_malformed_or_oversized_parent_dropped(self, server, bad):
        rec, url = server
        code, body = self._post_hdrs(
            url, {"tokens": [1, 2], "max_new_tokens": 2},
            {"X-Trace-Id": "prop-bad-" + str(len(bad)),
             "X-Parent-Span": bad})
        assert code == 200
        start = self._start_for(rec, body["trace_id"])[0]
        assert "parent" not in start

    def test_sampled_header_needs_valid_trace_id(self, server):
        rec, url = server
        code, body = self._post_hdrs(
            url, {"tokens": [1, 2], "max_new_tokens": 2},
            {"X-Trace-Sampled": "1"})  # no trace id: not trusted
        assert code == 200
        start = self._start_for(rec, body["trace_id"])[0]
        fin = self._fin_for(rec, start["id"])
        assert "retained" not in fin["a"]
