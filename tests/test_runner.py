"""Launcher tests (reference: test/test_run.py — arg parsing, config-file
precedence, command construction with mocked exec; plus a REAL 2-process
local launch, which the reference only gets via CI's mpirun wrapper)."""

import os
import sys
import textwrap
import threading

import pytest

from horovod_tpu.runner import config_parser, launch, rendezvous
from horovod_tpu.runner.hosts import HostSpec, SlotInfo, allocate, parse_hosts
from horovod_tpu.runner.run import parse_args, _run


class TestHostParsing:
    def test_hosts_string(self):
        specs = parse_hosts("a:4,b:8")
        assert specs == [HostSpec("a", 4), HostSpec("b", 8)]

    def test_host_no_slots(self):
        assert parse_hosts("a,b") == [HostSpec("a", 1), HostSpec("b", 1)]

    def test_hostfile(self, tmp_path):
        f = tmp_path / "hosts"
        f.write_text("# comment\nnode1 slots=4\nnode2 slots=2\n\n")
        assert parse_hosts(hostfile=str(f)) == [
            HostSpec("node1", 4),
            HostSpec("node2", 2),
        ]

    def test_both_raises(self):
        with pytest.raises(ValueError):
            parse_hosts("a:1", "file")

    def test_default_localhost(self):
        assert parse_hosts() == [HostSpec("localhost", 0)]

    def test_allocate(self):
        slots = allocate([HostSpec("a", 4), HostSpec("b", 4)])
        assert slots[0].rank == 0 and slots[1].rank == 1
        assert all(s.size == 2 for s in slots)
        assert all(s.world_chips == 8 for s in slots)
        env = slots[1].to_env()
        assert env["HOROVOD_RANK"] == "1"
        assert env["HOROVOD_CROSS_SIZE"] == "2"
        assert env["HOROVOD_LOCAL_SIZE"] == "4"


class TestArgsAndConfig:
    def test_basic_parse(self):
        args = parse_args(["-np", "2", "-H", "h1:4,h2:4", "python", "train.py"])
        assert args.np == 2
        assert args.hosts == "h1:4,h2:4"
        assert args.command == ["python", "train.py"]

    def test_flag_groups(self):
        args = parse_args(
            [
                "--fusion-threshold-mb", "32",
                "--autotune",
                "--timeline-filename", "/tmp/t.json",
                "--no-stall-check",
                "--log-level", "DEBUG",
                "cmd",
            ]
        )
        env = config_parser.set_env_from_args({}, args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
        assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
        assert env["HOROVOD_LOG_LEVEL"] == "DEBUG"

    def test_config_file_and_cli_precedence(self, tmp_path):
        """CLI flags beat config-file values (test_run.py:176-233)."""
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text(
            textwrap.dedent(
                """
                params:
                  fusion-threshold-mb: 16
                  cycle-time-ms: 3.5
                autotune:
                  enabled: true
                  warmup-samples: 5
                timeline:
                  filename: /tmp/from_config.json
                stall-check:
                  disable: false
                  warning-time-seconds: 120
                """
            )
        )
        args = parse_args(
            ["--fusion-threshold-mb", "64", "--config-file", str(cfg), "cmd"]
        )
        config_parser.apply_config_file(args, args.config_file)
        assert args.fusion_threshold_mb == 64.0  # CLI wins
        assert args.cycle_time_ms == 3.5  # config applies
        assert args.autotune is True
        assert args.autotune_warmup_samples == 5
        assert args.timeline_filename == "/tmp/from_config.json"
        assert args.stall_check_warning_time_seconds == 120

    def test_version(self, capsys):
        args = parse_args(["--version"])
        assert _run(args) == 0
        import horovod_tpu

        assert horovod_tpu.__version__ in capsys.readouterr().out

    def test_no_command(self):
        with pytest.raises(SystemExit):
            _run(parse_args(["-np", "1"]))


class TestSshPreflight:
    def test_local_hosts_skip_probe(self, monkeypatch):
        from horovod_tpu.runner import run as run_mod

        import subprocess

        def boom(*a, **k):
            raise AssertionError("must not probe local hosts")

        monkeypatch.setattr(subprocess, "run", boom)
        run_mod.check_hosts_ssh(["localhost", "127.0.0.1"])  # no raise

    def test_unreachable_host_fails_fast(self, monkeypatch, tmp_path):
        from horovod_tpu.runner import cache as cache_mod
        from horovod_tpu.runner import run as run_mod

        import subprocess

        monkeypatch.setattr(cache_mod, "DEFAULT_PATH",
                            str(tmp_path / "cache.json"))

        class R:
            returncode = 255

        calls = []

        def fake_run(cmd, **k):
            calls.append(cmd)
            return R()

        monkeypatch.setattr(subprocess, "run", fake_run)
        with pytest.raises(SystemExit, match="badhost"):
            run_mod.check_hosts_ssh(["badhost", "localhost"])
        assert len(calls) == 1  # only the remote host probed

    def test_success_cached(self, monkeypatch, tmp_path):
        from horovod_tpu.runner import cache as cache_mod
        from horovod_tpu.runner import run as run_mod

        import subprocess

        monkeypatch.setattr(cache_mod, "DEFAULT_PATH",
                            str(tmp_path / "cache.json"))

        class R:
            returncode = 0

        calls = []

        def fake_run(cmd, **k):
            calls.append(cmd)
            return R()

        monkeypatch.setattr(subprocess, "run", fake_run)
        run_mod.check_hosts_ssh(["far1", "far2"])
        assert len(calls) == 2
        run_mod.check_hosts_ssh(["far1", "far2"])  # cache hit: no probes
        assert len(calls) == 2
        run_mod.check_hosts_ssh(["far1"], use_cache=False)  # forced
        assert len(calls) == 3


class TestCache:
    def test_roundtrip_and_ttl(self, tmp_path):
        from horovod_tpu.runner.cache import Cache

        c = Cache(str(tmp_path / "c.json"), ttl_seconds=1000)
        assert c.get("k") is None
        c.put("k", {"a": 1})
        assert c.get("k") == {"a": 1}
        expired = Cache(str(tmp_path / "c.json"), ttl_seconds=0)
        assert expired.get("k") is None

    def test_corrupt_file_is_empty(self, tmp_path):
        from horovod_tpu.runner.cache import Cache

        p = tmp_path / "c.json"
        p.write_text("{not json")
        c = Cache(str(p))
        assert c.get("k") is None
        c.put("k", 1)  # must not raise
        assert c.get("k") == 1


class TestRendezvous:
    def test_kv_roundtrip(self):
        server = rendezvous.RendezvousServer()
        port = server.start()
        try:
            client = rendezvous.KVClient("127.0.0.1", port)
            assert client.get("scope", "k") is None
            client.put("scope", "k", b"value")
            assert client.get("scope", "k") == b"value"
            assert client.wait("scope", "k") == b"value"
            client.delete_scope("scope")
            assert client.get("scope", "k") is None
        finally:
            server.stop()

    def test_wait_timeout(self):
        server = rendezvous.RendezvousServer()
        port = server.start()
        try:
            client = rendezvous.KVClient("127.0.0.1", port)
            with pytest.raises(TimeoutError):
                client.wait("s", "missing", timeout=0.3)
        finally:
            server.stop()

    def test_concurrent_publish(self):
        server = rendezvous.RendezvousServer()
        port = server.start()
        try:
            client = rendezvous.KVClient("127.0.0.1", port)

            def pub(i):
                client.put("s", f"k{i}", str(i).encode())

            ts = [threading.Thread(target=pub, args=(i,)) for i in range(8)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            for i in range(8):
                assert client.get("s", f"k{i}") == str(i).encode()
        finally:
            server.stop()

    def test_scope_listing_and_server_side_access(self):
        """GET /scope/ lists keys (elastic heartbeat scanning), and the
        supervisor-side server helpers interoperate with signed client
        writes."""
        server = rendezvous.RendezvousServer()
        port = server.start()
        try:
            client = rendezvous.KVClient("127.0.0.1", port)
            client.put("hb", "r0", b"1.0")
            client.put("hb", "r1", b"2.0")
            assert client.keys("hb") == ["r0", "r1"]
            assert server.keys("hb") == ["r0", "r1"]
            assert server.get("hb", "r1") == b"2.0"
            server.put("hb", "r2", b"3.0")
            assert client.get("hb", "r2") == b"3.0"
            server.clear_scope("hb")
            assert client.keys("hb") == []
            assert client.get("hb", "r0") is None
        finally:
            server.stop()


class TestHostDiscovery:
    def test_fixed(self):
        from horovod_tpu.runner.discovery import FixedHostDiscovery

        specs = [HostSpec("a", 4), HostSpec("b", 4)]
        assert FixedHostDiscovery(specs).find_available_hosts() == specs

    def test_script(self, tmp_path):
        from horovod_tpu.runner.discovery import ScriptHostDiscovery

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\n"
                          "echo 'node1:4'\n"
                          "echo '# stale entry'\n"
                          "echo 'node2'\n")
        script.chmod(0o755)
        specs = ScriptHostDiscovery(str(script)).find_available_hosts()
        assert specs == [HostSpec("node1", 4), HostSpec("node2", 1)]

    def test_failing_script_yields_empty(self, tmp_path):
        from horovod_tpu.runner.discovery import ScriptHostDiscovery

        assert ScriptHostDiscovery("exit 3").find_available_hosts() == []


class TestBlacklist:
    def test_cooldown_expiry(self):
        from horovod_tpu.runner.hosts import Blacklist

        clock = [0.0]
        b = Blacklist(cooldown=5.0, _clock=lambda: clock[0])
        b.add("bad")
        assert "bad" in b and b.hosts() == ["bad"]
        assert b.filter([HostSpec("bad", 1), HostSpec("ok", 1)]) == [
            HostSpec("ok", 1)]
        clock[0] = 5.1  # cooldown elapsed: host readmitted
        assert "bad" not in b and b.hosts() == []
        b.add("bad")
        assert b.failure_count("bad") == 2

    def test_forever(self):
        from horovod_tpu.runner.hosts import Blacklist

        b = Blacklist(cooldown=None)
        b.add("bad")
        assert "bad" in b


class TestLaunch:
    def test_command_construction_local(self):
        slot = SlotInfo("localhost", 0, 2, 4, 8)
        cmd, env, stdin = launch.build_command(
            slot, ["python", "t.py"], {"PATH": "/bin"}, "127.0.0.1", 5000
        )
        assert stdin is None
        assert cmd == ["python", "t.py"]
        assert env["HOROVOD_RANK"] == "0"
        assert env["HOROVOD_COORDINATOR_ADDR"] == "127.0.0.1"
        assert env["HOROVOD_COORDINATOR_PORT"] == "5000"
        assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "5000"

    def test_command_construction_ssh(self):
        slot = SlotInfo("remotehost", 1, 2, 4, 8)
        cmd, _, _ = launch.build_command(
            slot, ["python", "t.py"], {}, "10.0.0.1", 5000
        )
        assert cmd[0] == "ssh"
        assert "remotehost" in cmd
        remote = cmd[-1]
        assert "HOROVOD_RANK=1" in remote
        assert "python t.py" in remote

    def test_mocked_launch_all_ranks(self):
        """Reference-style mocked exec: assert each rank got the right env
        (test_run.py:259-352 pattern)."""
        calls = []

        def fake_exec(cmd, env=None, **kw):
            calls.append((cmd, env))
            return 0

        rc = launch.launch_job(
            ["python", "x.py"],
            [HostSpec("localhost", 4), HostSpec("localhost", 4)],
            env={},
            _executor=fake_exec,
        )
        assert rc == 0
        assert len(calls) == 2
        ranks = sorted(int(env["HOROVOD_RANK"]) for _, env in calls)
        assert ranks == [0, 1]

    def test_failure_propagates(self):
        def fake_exec(cmd, env=None, **kw):
            return 3 if env["HOROVOD_RANK"] == "1" else 0

        rc = launch.launch_job(
            ["x"],
            [HostSpec("localhost", 1)] * 2,
            env={},
            _executor=fake_exec,
        )
        assert rc == 3

    @pytest.mark.slow
    def test_real_two_process_launch(self, tmp_path):
        """Actually spawn 2 local processes that rendezvous through the KV
        server and verify each other's ranks — real end-to-end launch."""
        script = tmp_path / "worker.py"
        script.write_text(
            textwrap.dedent(
                """
                import os, sys
                sys.path.insert(0, os.environ["REPO"])
                from horovod_tpu.runner.rendezvous import KVClient
                rank = os.environ["HOROVOD_RANK"]
                size = int(os.environ["HOROVOD_SIZE"])
                c = KVClient(os.environ["HOROVOD_COORDINATOR_ADDR"],
                             int(os.environ["HOROVOD_COORDINATOR_PORT"]))
                c.put("test", f"rank{rank}", rank.encode())
                for r in range(size):
                    assert c.wait("test", f"rank{r}", timeout=30).decode() == str(r)
                print(f"rank {rank} ok")
                """
            )
        )
        out = tmp_path / "out"
        env = {
            "PATH": os.environ.get("PATH", ""),
            "REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            # prevent the sandbox sitecustomize from grabbing the TPU
            "PALLAS_AXON_POOL_IPS": "",
        }
        rc = launch.launch_job(
            [sys.executable, str(script)],
            [HostSpec("localhost", 1)] * 2,
            env=env,
            output_filename=str(out),
        )
        assert rc == 0
        assert "ok" in (out / "rank.0.stdout").read_text()
        assert "ok" in (out / "rank.1.stdout").read_text()


    @pytest.mark.slow
    def test_sigterm_kills_term_swallowing_ranks(self, tmp_path):
        """SIGTERM to the launcher must reap ranks that CATCH SIGTERM
        (JAX installs a preemption notifier that swallows it): the
        launcher has to stay alive through the watchers' TERM -> grace ->
        KILL escalation instead of dying after a token sleep."""
        import signal
        import subprocess
        import time

        script = tmp_path / "stubborn.py"
        script.write_text(
            "import signal, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: None)\n"
            "print('ready', flush=True)\n"
            "time.sleep(600)\n"
        )
        driver = tmp_path / "driver.py"
        driver.write_text(textwrap.dedent(f"""
            import os, sys
            sys.path.insert(0, os.environ["REPO"])
            from horovod_tpu.runner import launch
            from horovod_tpu.runner.hosts import HostSpec
            launch.launch_job(
                [sys.executable, {str(script)!r}],
                [HostSpec("localhost", 1)] * 2,
                env={{"PATH": os.environ.get("PATH", ""),
                     "PALLAS_AXON_POOL_IPS": ""}},
                output_filename={str(tmp_path / "out")!r})
        """))
        env = dict(os.environ)
        env["REPO"] = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.Popen([sys.executable, str(driver)], env=env)
        # wait for both ranks to be up
        deadline = time.time() + 60
        outdir = tmp_path / "out"
        while time.time() < deadline:
            try:
                if all("ready" in (outdir / f"rank.{r}.stdout").read_text()
                       for r in (0, 1)):
                    break
            except OSError:
                pass
            time.sleep(0.3)
        else:
            proc.kill()
            raise AssertionError("ranks never came up")
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
        # after the escalation window, no stubborn.py processes survive
        time.sleep(1.0)
        left = subprocess.run(
            ["pgrep", "-f", "stubborn.py"], capture_output=True
        ).stdout.decode().split()
        left = [p for p in left
                if subprocess.run(["ps", "-o", "comm=", "-p", p],
                                  capture_output=True
                                  ).stdout.decode().strip() == "python"]
        assert not left, f"orphaned rank processes: {left}"

    def test_ssh_secret_rides_stdin_not_argv(self):
        """The per-job HMAC key must never appear on a remote command line
        (visible via /proc/<pid>/cmdline to any local user)."""
        slot = SlotInfo("remotehost", 1, 2, 4, 8)
        cmd, _, stdin = launch.build_command(
            slot, ["python", "t.py"], {"HOROVOD_SECRET_KEY": "deadbeef"},
            "10.0.0.1", 5000
        )
        assert "deadbeef" not in " ".join(cmd)
        assert stdin == b"deadbeef\n"
        assert "read -r HOROVOD_SECRET_KEY" in cmd[-1]
