"""Continuous-batching inference engine (horovod_tpu/serving/).

The gold check is TOKEN-IDENTITY: whatever mix of requests shares the
slot pool, whenever they were admitted, each one's greedy output must
equal per-request ``greedy_decode`` — plus ZERO recompilations of the
decode executable after warmup (the engine's compile-count hook).
Everything runs on JAX_PLATFORMS=cpu with a tiny TransformerConfig and
S <= 4 slots so the suite stays tier-1-fast; the HTTP soak test is
marked ``slow``.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serving
from horovod_tpu.models import transformer as T

pytestmark = pytest.mark.serving


def _cfg(**kw):
    import dataclasses

    base = T.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention_impl="reference",
        n_kv_heads=2)
    return dataclasses.replace(base, **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return T.init_params(jax.random.PRNGKey(0), cfg), cfg


def _ref_greedy(params, cfg, prompt, steps):
    return np.asarray(T.greedy_decode(
        params, jnp.asarray([prompt], jnp.int32), steps, cfg))[0].tolist()


def _run_until_done(engine, futs, max_ticks=200):
    for _ in range(max_ticks):
        if all(f.done() for f in futs):
            return
        engine.step()
    raise AssertionError("engine did not finish within the tick budget")


class TestSlotCache:
    def test_alloc_free_fcfs_lowest(self, model):
        _, cfg = model
        slots = serving.SlotCache(cfg, 3, max_len=16)
        assert [slots.alloc() for _ in range(3)] == [0, 1, 2]
        assert slots.alloc() is None and slots.free_count == 0
        slots.free(1)
        slots.free(0)
        assert slots.alloc() == 0  # lowest index first, not LIFO
        assert slots.occupancy == pytest.approx(2 / 3)
        with pytest.raises(ValueError):
            slots.free(2), slots.free(2)

    def test_insert_prefill_lands_in_slot(self, model):
        params, cfg = model
        prompt = jnp.asarray([[5, 9, 2]], jnp.int32)
        pre_logits, pre = T.prefill(params, prompt,
                                    T.init_cache(cfg, 1, 8), cfg)
        slots = serving.SlotCache(cfg, 3, max_len=16)
        slots.alloc(), slots.alloc()
        slots.insert(1, pre)
        cache = slots.cache
        np.testing.assert_array_equal(
            np.asarray(cache["k"][:, 1, :, :8]), np.asarray(pre["k"][:, 0]))
        np.testing.assert_array_equal(
            np.asarray(cache["v"][:, 1, :, :8]), np.asarray(pre["v"][:, 0]))
        assert slots.positions().tolist() == [0, 3, 0]
        # untouched slots stay zero
        assert not np.asarray(cache["k"][:, 0]).any()

    def test_insert_requires_allocated_slot(self, model):
        params, cfg = model
        _, pre = T.prefill(params, jnp.asarray([[1]], jnp.int32),
                           T.init_cache(cfg, 1, 8), cfg)
        slots = serving.SlotCache(cfg, 2, max_len=16)
        with pytest.raises(ValueError):
            slots.insert(0, pre)


class TestDecodeStepSlots:
    @pytest.mark.slow
    def test_matches_per_request_decode_step(self, model):
        """Row s of the masked slot decode == batch-1 decode_step at that
        slot's own position, for slots at DIFFERENT depths."""
        params, cfg = model
        prompts = [[3, 4, 5, 6], [10, 11]]
        slots = serving.SlotCache(cfg, 3, max_len=16)
        singles = []
        for s, p in enumerate(prompts):
            slots.alloc()
            _, pre = T.prefill(params, jnp.asarray([p], jnp.int32),
                               T.init_cache(cfg, 1, len(p)), cfg)
            slots.insert(s, pre)
            _, single = T.prefill(params, jnp.asarray([p], jnp.int32),
                                  T.init_cache(cfg, 1, 16), cfg)
            singles.append(single)

        active = jnp.asarray([True, True, False])
        tokens = jnp.asarray([7, 12, 0], jnp.int32)
        for _ in range(3):
            logits, cache = T.decode_step_slots(
                params, tokens, slots.cache, cfg, active)
            slots.cache = cache
            for s in range(2):
                ref_logits, singles[s] = T.decode_step(
                    params, tokens[s:s + 1], singles[s], cfg)
                np.testing.assert_allclose(
                    np.asarray(logits[s]), np.asarray(ref_logits[0]),
                    atol=1e-4, rtol=1e-4)
            tokens = jnp.argmax(logits, -1).astype(jnp.int32)
        # inactive slot never advances
        assert slots.positions().tolist()[2] == 0

    def test_eager_capacity_guard(self, model):
        params, cfg = model
        slots = serving.SlotCache(cfg, 2, max_len=4)
        slots.cache["pos"] = jnp.asarray([4, 0], jnp.int32)
        with pytest.raises(ValueError, match="capacity"):
            T.decode_step_slots(params, jnp.zeros(2, jnp.int32),
                                slots.cache, cfg,
                                jnp.asarray([True, False]))


class TestEngineCorrectness:
    @pytest.mark.slow
    def test_token_identity_staggered_admission(self, model):
        """ACCEPTANCE: >= 3 concurrently admitted requests with unequal
        prompt lengths, admitted at different ticks, each token-identical
        to sequential greedy_decode — with zero decode recompilations
        after warmup."""
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=4, max_len=40, max_prefills_per_tick=1,
                min_prefill_bucket=4))
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in (3, 9, 5, 12)]
        steps = 11

        futs = [engine.submit(prompts[0], max_new_tokens=steps)]
        engine.step()          # admit r0 + warmup decode tick
        warm = engine.decode_compilations
        assert warm == 1
        futs.append(engine.submit(prompts[1], max_new_tokens=steps))
        engine.step()          # r1 admitted while r0 mid-decode
        futs.append(engine.submit(prompts[2], max_new_tokens=steps))
        futs.append(engine.submit(prompts[3], max_new_tokens=steps))
        _run_until_done(engine, futs)

        for p, f in zip(prompts, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, steps)
            assert f.finish_reason == "length"
        # the acceptance hook: the decode executable never recompiled
        assert engine.decode_compilations == warm == 1
        assert engine.stats()["requests_completed"] == 4

    @pytest.mark.slow
    def test_slot_reuse_no_contamination(self, model):
        """More requests than slots: retirement frees slots that later
        requests reuse; every output must still match per-request
        greedy_decode (stale K/V never attended)."""
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=2, max_len=40, max_prefills_per_tick=2,
                min_prefill_bucket=4, max_queue_depth=8))
        rng = np.random.default_rng(11)
        cases = [(rng.integers(0, cfg.vocab_size, n).tolist(), s)
                 for n, s in ((4, 6), (8, 3), (2, 9), (6, 5), (3, 7))]
        futs = [engine.submit(p, max_new_tokens=s) for p, s in cases]
        _run_until_done(engine, futs)
        for (p, s), f in zip(cases, futs):
            assert f.result(timeout=0) == _ref_greedy(params, cfg, p, s)
        assert engine.decode_compilations == 1
        assert engine.stats()["requests_completed"] == 5

    def test_eos_retirement(self, model):
        params, cfg = model
        prompt = [3, 4, 5]
        ref = _ref_greedy(params, cfg, prompt, 12)
        eos = ref[4]  # stop mid-stream at a token greedy really emits
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        fut = engine.submit(prompt, max_new_tokens=12, eos_id=eos)
        _run_until_done(engine, [fut])
        out = fut.result(timeout=0)
        assert fut.finish_reason == "eos"
        assert out == ref[:ref.index(eos) + 1]
        assert engine.slots.active_count == 0  # slot freed on retirement

    def test_first_token_eos_retires_at_admission(self, model):
        params, cfg = model
        prompt = [3, 4, 5]
        ref = _ref_greedy(params, cfg, prompt, 1)
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        fut = engine.submit(prompt, max_new_tokens=8, eos_id=ref[0])
        engine.step()
        assert fut.result(timeout=0) == ref
        assert fut.finish_reason == "eos"
        assert engine.slots.active_count == 0

    def test_streaming_callback_and_detokenize(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4),
            detokenize=lambda t: f"<{t}>")
        seen = []
        fut = engine.submit([3, 4], max_new_tokens=4,
                            on_token=lambda tok, piece: seen.append(
                                (tok, piece)))
        _run_until_done(engine, [fut])
        toks = fut.result(timeout=0)
        assert [t for t, _ in seen] == toks
        assert fut.text == "".join(f"<{t}>" for t in toks)


class TestAdmissionControl:
    def test_queue_full_typed_rejection(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              max_queue_depth=2,
                                              min_prefill_bucket=4))
        engine.submit([1], max_new_tokens=2)
        engine.submit([2], max_new_tokens=2)
        with pytest.raises(serving.QueueFullError):
            engine.submit([3], max_new_tokens=2)
        assert engine.stats()["requests_rejected"] == 1

    def test_deadline_exceeded_typed_rejection(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        fut = engine.submit([1, 2], max_new_tokens=4,
                            deadline=time.monotonic() - 0.01)
        engine.step()
        with pytest.raises(serving.DeadlineExceededError):
            fut.result(timeout=1.0)
        assert engine.stats()["requests_rejected"] == 1
        assert engine.stats()["requests_admitted"] == 0

    def test_deadline_after_admission_retires_slot(self, model):
        """A deadline lapsing AFTER admission frees the slot with a
        partial result (finish_reason 'deadline') instead of decoding
        to max_new_tokens for a caller that already timed out."""
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        fut = engine.submit([1, 2], max_new_tokens=16,
                            deadline=time.monotonic() + 60)
        engine.step()  # admit: first token emitted, slot occupied
        assert engine.slots.active_count == 1
        engine._states[0].request.deadline = time.monotonic() - 1
        engine.step()
        assert fut.done() and fut.finish_reason == "deadline"
        assert 1 <= len(fut.result(timeout=0)) < 16
        assert engine.slots.active_count == 0

    def test_rejected_counts_both_paths(self, model):
        """metrics.rejected sees BOTH rejection paths: submit-time
        QueueFullError (via the scheduler's constructor on_reject) and
        take-time DeadlineExceededError — /stats never under-reports
        shed load."""
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              max_queue_depth=1,
                                              min_prefill_bucket=4))
        # take-time path: queued past its deadline
        fut = engine.submit([1, 2], max_new_tokens=2,
                            deadline=time.monotonic() - 0.01)
        # submit-time path: queue (depth 1) already full
        with pytest.raises(serving.QueueFullError):
            engine.submit([3, 4], max_new_tokens=2)
        assert engine.stats()["requests_rejected"] == 1  # submit-time
        engine.step()
        with pytest.raises(serving.DeadlineExceededError):
            fut.result(timeout=1.0)
        assert engine.stats()["requests_rejected"] == 2  # + take-time

    def test_requeue_front_restores_fcfs_and_ignores_depth_bound(self):
        """The resume path's re-admission hook: requeued requests keep
        their ORIGINAL (older) ids — the real resume/preemption paths
        preserve them — so the scheduling order places them ahead of
        everything younger in their class, and they are exempt from
        max_queue_depth (their callers already hold live futures)."""
        class _F:
            def done(self):
                return False
            cancel_requested = False

        sched = serving.Scheduler(max_queue_depth=2)
        # Resumed requests were submitted (and got their ids) BEFORE
        # the still-queued one, exactly like a real crash window.
        r1 = serving.Request(prompt=[1], max_new_tokens=1, future=_F())
        r2 = serving.Request(prompt=[2], max_new_tokens=1, future=_F())
        r3 = serving.Request(prompt=[3], max_new_tokens=1, future=_F())
        queued = serving.Request(prompt=[9], max_new_tokens=1, future=_F())
        sched.submit(queued)
        sched.requeue_front([r1, r2, r3])  # depth 4 > bound 2: allowed
        assert sched.depth == 4
        out = sched.take(free_slots=4)
        # resumed requests first, in id (original FCFS) order
        assert [r.prompt for r in out[:2]] == [[1], [2]]
        out += sched.take(free_slots=4)
        assert [r.prompt for r in out] == [[1], [2], [3], [9]]

    def test_request_too_long_typed_rejection(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=16,
                                              min_prefill_bucket=4))
        with pytest.raises(serving.RequestTooLongError):
            engine.submit(list(range(10)), max_new_tokens=8)
        # boundary: prompt + max_new - 1 == capacity is admissible
        fut = engine.submit(list(range(9)), max_new_tokens=8)
        _run_until_done(engine, [fut])
        assert len(fut.result(timeout=0)) == 8

    def test_metrics_populated(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        futs = [engine.submit([1, 2, 3], max_new_tokens=3)
                for _ in range(2)]
        _run_until_done(engine, futs)
        s = engine.stats()
        assert s["requests_admitted"] == 2
        assert s["requests_completed"] == 2
        assert s["tokens_generated"] == 6
        assert s["ttft_seconds"]["count"] == 2
        assert s["ttft_seconds"]["p50"] is not None
        # 2 requests x 2 decode-step tokens each (first came from prefill)
        assert s["token_latency_seconds"]["count"] == 4
        assert s["decode_compilations"] == 1


class TestHistogram:
    def test_percentiles_and_snapshot(self):
        h = serving.Histogram(buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.05, 0.5, 20.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 4
        assert snap["buckets"] == {"0.1": 2, "1": 1, "10": 0, "+Inf": 1}
        assert h.percentile(0.5) == 0.1
        assert h.percentile(0.99) == 10.0  # +Inf reports largest edge
        assert serving.Histogram().percentile(0.5) is None


from conftest import http_post_json as _post  # noqa: E402


class TestServer:
    @pytest.fixture()
    def served(self, model):
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(n_slots=2, max_len=40,
                                              min_prefill_bucket=4))
        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            yield engine, f"http://{host}:{port}"

    def test_generate_healthz_stats(self, served, model):
        params, cfg = model
        engine, base = served
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "healthy"
        code, out = _post(base + "/generate",
                          {"tokens": [3, 4, 5], "max_new_tokens": 5})
        assert code == 200
        assert out["tokens"] == _ref_greedy(params, cfg, [3, 4, 5], 5)
        assert out["finish_reason"] == "length"
        assert out["ttft_ms"] > 0
        with urllib.request.urlopen(base + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["requests_completed"] == 1
        assert stats["decode_compilations"] == 1

    def test_http_typed_rejections(self, served):
        _, base = served
        code, out = _post(base + "/generate",
                          {"tokens": list(range(60)),
                           "max_new_tokens": 8})
        assert (code, out["type"]) == (413, "too_long")
        code, out = _post(base + "/generate", {"tokens": []})
        assert code == 400
        code, out = _post(base + "/generate",
                          {"text": "no encoder configured"})
        assert code == 400

    @pytest.mark.slow
    def test_soak_concurrent_clients(self, model):
        """Soak: many concurrent HTTP clients with mixed lengths; every
        response token-identical to sequential greedy_decode and no
        decode recompilation under the whole load."""
        params, cfg = model
        engine = serving.InferenceEngine(
            params, cfg, serving.EngineConfig(
                n_slots=4, max_len=40, max_queue_depth=64,
                min_prefill_bucket=4))
        rng = np.random.default_rng(3)
        cases = [(rng.integers(0, cfg.vocab_size, int(n)).tolist(), int(s))
                 for n, s in zip(rng.integers(2, 12, 24),
                                 rng.integers(2, 10, 24))]
        results = [None] * len(cases)

        with serving.ServingServer(engine, port=0) as srv:
            host, port = srv.address
            base = f"http://{host}:{port}"

            def client(i):
                p, s = cases[i]
                results[i] = _post(base + "/generate",
                                   {"tokens": p, "max_new_tokens": s})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(cases))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
        for (p, s), r in zip(cases, results):
            assert r is not None and r[0] == 200
            assert r[1]["tokens"] == _ref_greedy(params, cfg, p, s)
        assert engine.decode_compilations == 1
