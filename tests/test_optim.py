"""DistributedOptimizer / DistributedGradientTape / fusion tests
(reference: test_torch.py optimizer tests, test_tensorflow.py
DistributedGradientTape tests, backward_passes_per_step)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import spmd
from horovod_tpu.ops import fusion

N = 8


def _loss(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(N * 4, 3).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5]], np.float32)
    y = x @ w + 0.1 * rng.randn(N * 4, 1).astype(np.float32)
    return x, y


def _params():
    return {
        "w": jnp.zeros((3, 1), jnp.float32),
        "b": jnp.zeros((1,), jnp.float32),
    }


class TestDistributedOptimizer:
    def test_matches_global_batch_sgd(self):
        """DP train step with DistributedOptimizer == single-worker step on
        the full batch (the defining correctness property of gradient
        averaging)."""
        x, y = _data()
        params = _params()
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        step = spmd.make_train_step(_loss, opt, donate=False)
        opt_state = opt.init(params)
        p2, _, loss = step(params, opt_state, (x, y))

        # Single-process oracle on the full batch:
        g = jax.grad(_loss)(params, (x, y))
        expect = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(expect[k]), rtol=1e-4, atol=1e-5
            )
        assert np.isfinite(float(loss))

    def test_sum_op_scales(self):
        x, y = _data()
        params = _params()
        opt_avg = hvd.DistributedOptimizer(optax.sgd(0.1), op=hvd.Average)
        opt_sum = hvd.DistributedOptimizer(optax.sgd(0.1 / N), op=hvd.Sum)
        s_avg = spmd.make_train_step(_loss, opt_avg, donate=False)
        s_sum = spmd.make_train_step(_loss, opt_sum, donate=False)
        pa, _, _ = s_avg(params, opt_avg.init(params), (x, y))
        ps, _, _ = s_sum(params, opt_sum.init(params), (x, y))
        np.testing.assert_allclose(
            np.asarray(pa["w"]), np.asarray(ps["w"]), rtol=1e-4, atol=1e-6
        )

    def test_training_converges(self):
        x, y = _data()
        params = _params()
        opt = hvd.DistributedOptimizer(optax.adam(0.05))
        step = spmd.make_train_step(_loss, opt)
        opt_state = opt.init(params)
        losses = []
        for _ in range(60):
            params, opt_state, loss = step(params, opt_state, (x, y))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.1
        np.testing.assert_allclose(np.asarray(params["w"]).ravel(), [1, -2, 0.5], atol=0.3)

    def test_adasum_op(self):
        x, y = _data()
        params = _params()
        opt = hvd.DistributedOptimizer(optax.sgd(0.05), op=hvd.Adasum)
        step = spmd.make_train_step(_loss, opt)
        opt_state = opt.init(params)
        for _ in range(40):
            params, opt_state, loss = step(params, opt_state, (x, y))
        assert float(loss) < 1.0


class TestDistributedAdasumOptimizer:
    """Delta-model Adasum (reference tensorflow/__init__.py:313-407,
    torch/__init__.py:219-407): the LOCAL optimizer update — not the
    gradient — is Adasum-combined.  Oracle: adasum_reduce_stack over the
    per-worker deltas."""

    def _worker_deltas(self, params, x, y, lr):
        """Per-worker sgd deltas for each of the N batch shards."""
        from horovod_tpu.ops import adasum as AD

        shard = len(x) // N
        deltas = []
        for i in range(N):
            b = (x[i * shard:(i + 1) * shard], y[i * shard:(i + 1) * shard])
            g = jax.grad(_loss)(params, b)
            deltas.append(jax.tree_util.tree_map(lambda gg: -lr * gg, g))
        return {
            k: AD.adasum_reduce_stack(
                jnp.stack([d[k] for d in deltas]))
            for k in params
        }

    def test_one_step_matches_pairwise_oracle(self):
        x, y = _data()
        params = _params()
        opt = hvd.DistributedAdasumOptimizer(optax.sgd(0.1))
        step = spmd.make_train_step(_loss, opt, donate=False)
        p2, _, _ = step(params, opt.init(params), (x, y))

        global_delta = self._worker_deltas(params, x, y, 0.1)
        for k in params:
            expect = params[k] + global_delta[k]
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(expect), rtol=1e-5, atol=1e-6)

    def test_adaptive_inner_optimizer(self):
        """The combined quantity must carry the inner optimizer's adaptive
        scaling (here: adam), not the raw gradient."""
        x, y = _data()
        params = _params()
        inner = optax.adam(0.05)
        opt = hvd.DistributedAdasumOptimizer(inner)
        step = spmd.make_train_step(_loss, opt, donate=False)
        p2, _, _ = step(params, opt.init(params), (x, y))

        from horovod_tpu.ops import adasum as AD

        shard = len(x) // N
        deltas = []
        for i in range(N):
            b = (x[i * shard:(i + 1) * shard], y[i * shard:(i + 1) * shard])
            g = jax.grad(_loss)(params, b)
            u, _ = inner.update(g, inner.init(params), params)
            deltas.append(u)
        for k in params:
            expect = params[k] + AD.adasum_reduce_stack(
                jnp.stack([d[k] for d in deltas]))
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(expect), rtol=1e-5, atol=1e-6)

    def test_identical_workers_halve_like_adasum(self):
        """All workers computing the SAME delta must produce that delta
        (Adasum's a==b case: coefficients sum to 1), not N× it."""
        x, y = _data()
        params = _params()
        # Replicate one shard to every worker so all grads are identical.
        xs = np.tile(x[:4], (N, 1))
        ys = np.tile(y[:4], (N, 1))
        opt = hvd.DistributedAdasumOptimizer(optax.sgd(0.1))
        step = spmd.make_train_step(_loss, opt, donate=False)
        p2, _, _ = step(params, opt.init(params), (xs, ys))
        g = jax.grad(_loss)(params, (xs[:4], ys[:4]))
        for k in params:
            expect = params[k] - 0.1 * g[k]
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(expect), rtol=1e-5, atol=1e-6)

    def test_backward_passes_per_step_drift_and_sync(self):
        """k=2: step 1 applies the local update only (workers drift);
        step 2 Adasum-combines the CUMULATIVE drift from start."""
        x, y = _data()
        params = _params()
        lr = 0.1
        opt = hvd.DistributedAdasumOptimizer(
            optax.sgd(lr), backward_passes_per_step=2)
        step = spmd.make_train_step(_loss, opt, donate=False)
        opt_state = opt.init(params)
        p1, opt_state, _ = step(params, opt_state, (x, y))
        p2, opt_state, _ = step(p1, opt_state, (x, y))

        # Oracle: simulate each worker's two local sgd steps from start.
        from horovod_tpu.ops import adasum as AD

        shard = len(x) // N
        deltas = []
        for i in range(N):
            b = (x[i * shard:(i + 1) * shard], y[i * shard:(i + 1) * shard])
            local = params
            for _ in range(2):
                g = jax.grad(_loss)(local, b)
                local = jax.tree_util.tree_map(
                    lambda p, gg: p - lr * gg, local, g)
            deltas.append(jax.tree_util.tree_map(
                lambda l, s: l - s, local, params))
        for k in params:
            expect = params[k] + AD.adasum_reduce_stack(
                jnp.stack([d[k] for d in deltas]))
            np.testing.assert_allclose(
                np.asarray(p2[k]), np.asarray(expect), rtol=1e-5, atol=1e-6)

    def test_bad_k(self):
        with pytest.raises(ValueError):
            hvd.DistributedAdasumOptimizer(
                optax.sgd(0.1), backward_passes_per_step=0)


class TestBackwardPassesPerStep:
    def test_accumulation(self):
        """k accumulation steps then one update == one update with the
        averaged gradient (torch/__init__.py:95-157 semantics)."""
        k = 4
        x, y = _data()
        params = _params()
        opt = hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=k)
        step = spmd.make_train_step(_loss, opt, donate=False)
        opt_state = opt.init(params)
        p = params
        for i in range(k):
            p, opt_state, _ = step(p, opt_state, (x, y))
            if i < k - 1:
                # no update applied yet
                np.testing.assert_allclose(
                    np.asarray(p["w"]), np.asarray(params["w"])
                )
        g = jax.grad(_loss)(params, (x, y))
        expect = params["w"] - 0.1 * g["w"]
        np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(expect), rtol=1e-4, atol=1e-6)

    def test_no_average_aggregated(self):
        k = 2
        x, y = _data()
        params = _params()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1),
            backward_passes_per_step=k,
            average_aggregated_gradients=False,
        )
        step = spmd.make_train_step(_loss, opt, donate=False)
        opt_state = opt.init(params)
        p = params
        for _ in range(k):
            p, opt_state, _ = step(p, opt_state, (x, y))
        g = jax.grad(_loss)(params, (x, y))
        expect = params["w"] - 0.1 * k * g["w"]  # sum of k identical grads
        np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(expect), rtol=1e-4, atol=1e-6)

    def test_bad_k(self):
        with pytest.raises(ValueError):
            hvd.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=0)


class TestDistributedGradientTape:
    def test_grads_averaged(self):
        x, y = _data()
        params = _params()

        def inner(xs, ys):
            tape = hvd.DistributedGradientTape(_loss)
            loss, grads = tape(params, (xs, ys))
            return grads["w"][None]

        out = jax.jit(
            spmd.shard(
                lambda xs, ys: inner(xs, ys),
                in_specs=(P(hvd.AXIS), P(hvd.AXIS)),
                out_specs=P(hvd.AXIS),
            )
        )(x, y)
        full = jax.grad(_loss)(params, (x, y))
        np.testing.assert_allclose(
            np.asarray(out[0]), np.asarray(full["w"]), rtol=1e-4, atol=1e-5
        )


class TestFusion:
    def test_buckets_respect_threshold_and_dtype(self):
        leaves = [np.ones(10, np.float32), np.ones(10, np.float32),
                  np.ones(10, np.int32), np.ones(1000, np.float32)]
        buckets = fusion.make_buckets(leaves, threshold=100)
        # int32 leaf must be in its own bucket; big leaf alone
        for b in buckets:
            dtypes = {np.asarray(leaves[i]).dtype for i in b}
            assert len(dtypes) == 1
        flat = sorted(i for b in buckets for i in b)
        assert flat == [0, 1, 2, 3]

    def test_fused_tree_matches_unfused(self):
        rng = np.random.RandomState(0)
        tree = {
            "a": rng.randn(N, 4).astype(np.float32),
            "b": rng.randn(N, 5).astype(np.float32),
            "c": rng.randn(N, 2, 3).astype(np.float32),
        }

        def inner(a, b, c):
            t = {"a": a[0], "b": b[0], "c": c[0]}
            out = fusion.fused_allreduce_tree(t, hvd.Sum, threshold=1 << 20)
            return jax.tree_util.tree_map(lambda l: l[None], out)

        out = jax.jit(
            spmd.shard(
                inner,
                in_specs=(P(hvd.AXIS),) * 3,
                out_specs={"a": P(hvd.AXIS), "b": P(hvd.AXIS), "c": P(hvd.AXIS)},
            )
        )(tree["a"], tree["b"], tree["c"])
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(out[k][0]), tree[k].sum(axis=0), rtol=1e-4, atol=1e-5
            )

    def test_tiny_threshold_many_buckets(self):
        leaves = [np.ones(100, np.float32) for _ in range(5)]
        buckets = fusion.make_buckets(leaves, threshold=1)
        assert len(buckets) == 5


class TestSparseGradients:
    """Row-sparse embedding-gradient reduction — the IndexedSlices
    allgather analogue (reference tensorflow/__init__.py:74-89)."""

    def _sparse_grad(self, V=64, D=8, rows=(3, 17, 40)):
        g = np.zeros((V, D), np.float32)
        for r in rows:
            g[r] = np.random.RandomState(r).randn(D)
        return g

    def test_matches_dense_allreduce(self):
        from horovod_tpu.ops import sparse as SP

        g = self._sparse_grad()
        for op in (hvd.Sum, hvd.Average):
            dense = np.asarray(hvd.allreduce(g, op, name=f"sp.ref.{op}"))
            sparse = SP.sparse_allreduce(g, op, name=f"sp.t.{op}")
            np.testing.assert_allclose(sparse, dense, rtol=1e-6,
                                       err_msg=op)

    def test_wire_bytes_proportional_to_touched_rows(self):
        from horovod_tpu.ops import sparse as SP

        g = self._sparse_grad(V=1000, D=16, rows=(1, 2, 3))
        out, stats = SP.sparse_allreduce(g, hvd.Average, name="sp.stats",
                                         return_stats=True)
        assert stats["rows"] == 3 and stats["total_rows"] == 1000
        # 3 touched rows of 1000: sparse wire bytes ~ 0.3% of dense.
        assert stats["sparse_bytes"] < stats["dense_bytes"] / 100
        np.testing.assert_allclose(
            out, np.asarray(hvd.allreduce(g, hvd.Average, name="sp.s2")),
            rtol=1e-6)

    def test_all_zero_gradient(self):
        from horovod_tpu.ops import sparse as SP

        g = np.zeros((16, 4), np.float32)
        out = SP.sparse_allreduce(g, hvd.Sum, name="sp.zero")
        np.testing.assert_array_equal(out, g)

    def test_optimizer_sparse_keys_matches_dense_path(self):
        """DistributedOptimizer(sparse_keys=('embed',)) must produce the
        same updates as the dense path — only the wire mechanism
        changes."""
        grads = {
            "embed": jnp.asarray(self._sparse_grad()),
            "dense": {"w": jnp.ones((5, 5)), "b": jnp.ones((5,))},
        }
        params = jax.tree_util.tree_map(jnp.zeros_like, grads)

        def run(**kw):
            opt = hvd.DistributedOptimizer(optax.sgd(1.0), **kw)
            state = opt.init(params)
            up, _ = opt.update(
                jax.tree_util.tree_map(np.asarray, grads), state, params)
            return up

        up_sparse = run(sparse_keys=("embed",))
        up_dense = run()
        for path, a in jax.tree_util.tree_leaves_with_path(up_sparse):
            b = dict(jax.tree_util.tree_leaves_with_path(up_dense))[path]
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6,
                                       err_msg=jax.tree_util.keystr(path))

    def test_traced_leaves_fall_back_dense(self):
        """Inside jit the sparse route must not engage (static shapes):
        the same sparse_keys optimizer works compiled, via shard_map."""
        from horovod_tpu import optim

        g = {"embed": jnp.ones((8, 4)), "w": jnp.ones((3,))}

        def fn(g):
            return optim.distributed_gradients(
                g, hvd.Average, sparse_keys=("embed",))

        out = spmd.run(fn, g, in_specs=P(), out_specs=P())
        np.testing.assert_allclose(np.asarray(out["embed"]),
                                   np.ones((8, 4)), rtol=1e-6)

    def test_adasum_op_rejected(self):
        from horovod_tpu.ops import sparse as SP

        with pytest.raises(ValueError, match="Sum/Average"):
            SP.sparse_allreduce(np.ones((4, 2), np.float32), hvd.Adasum)
